"""Request-level tracing + SLO plane for the serving tier.

The observability stack through PR 15 was process-centric: telemetry
aggregates, the flight ring, diagnostics spans — all answer "what is
this RANK doing", none answer "where did this REQUEST spend its time".
This module is the Dapper-style per-request half (PAPERS.md
trace-propagation template): a trace context minted at
``InferenceEngine.submit`` rides the :class:`ServeRequest` through
scheduler admission, batch assembly, the in-flight window, and the
completer, accumulating BOUNDARY stamps that telescope into contiguous
phase spans:

    admit | queue | assemble | dispatch | device | slice | settle

Because consecutive phases share their boundary timestamp, the span
durations of one trace sum EXACTLY to its end-to-end latency — there is
no untraced gap for time to hide in. Requests coalesced into one padded
micro-batch share the batch-wide stamps (one ``perf_counter`` read per
boundary per batch, not per request) and carry the same ``batch`` id;
the batch itself lands in a parallel ring with its member trace IDs —
the batch->request causality link. Shed and expired requests get a
terminal span named after the outcome with the shed reason, so dropped
traffic is visible in ``GET /traces`` instead of silently vanishing.

Sampling is head-based and deterministic: ``MXTPU_TRACE_SAMPLE`` is the
sampled fraction, decided once at submit by a counter (no RNG — rates
are exact, runs are reproducible). At 0 (the default) ``maybe_start``
returns None before touching anything, every engine hook degrades to
one ``is None`` check, and the serving path is bit-identical to the
untraced engine — the same inertness contract MXTPU_OPS_PORT-unset
keeps for opsd. Finished traces live in a bounded per-process ring
(``MXTPU_TRACE_RING``), snapshot by opsd's ``/traces``, bundled by
postmortem, and merged across ranks by ``tools/blackbox.py`` (span
timestamps are ``perf_counter`` — the same clock as diagnostics spans,
so request spans interleave with rank spans in one chrome trace).

On top rides the SLO plane — and unlike tracing it sees EVERY request
(objectives are evaluated on the full population, never a sample):
``MXTPU_SLO_<CLASS>_MS`` declares a per-class latency objective;
:func:`slo_observe` folds each finished request into a rolling window
(``MXTPU_SLO_WINDOW_S``) as good/bad against the objective (sheds,
timeouts, and errors are always bad); the burn rate is the windowed bad
fraction over the error budget ``1 - MXTPU_SLO_TARGET``. A class
burning hotter than ``MXTPU_SLO_BURN_MAX`` (with at least
``MXTPU_SLO_MIN_EVENTS`` events in window) flips opsd ``/readyz`` to
503 — the front door and fleet LBs stop routing to the replica — and
recovery is automatic once the window rolls the violations off.
Burn rates are published as ``serve_slo_burn_rate`` gauges.

Stdlib-only; telemetry is reached lazily and guarded — a broken
observability layer must never take the serving path down with it.
See docs/observability.md §6.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time

__all__ = [
    "PHASES", "ReqTrace",
    "sample_rate", "enabled", "maybe_start", "next_batch_id",
    "finish", "record_batch",
    "traces", "batches", "phase_summary",
    "ring_capacity", "set_ring_capacity", "reset",
    "slo_objective_ms", "set_slo_objective", "slo_observe",
    "slo_status", "slo_burning",
]

#: Phase vocabulary, in pipeline order. Each phase is closed by the next
#: boundary stamp; the terminal phase (settle, or the failure outcome)
#: closes at finish time.
PHASES = ("admit", "queue", "assemble", "dispatch", "device", "slice",
          "settle")

# boundary stamp -> the phase it CLOSES (submit time opens "admit")
_PHASE_OF = {
    "admitted": "admit",        # scheduler.offer accepted the request
    "assembling": "queue",      # the assembler picked it into a batch
    "dispatching": "assemble",  # host pad/concat done, issuing dispatch
    "dispatched": "dispatch",   # async dispatch returned
    "ready": "device",          # output buffers exist
    "sliced": "slice",          # this request's rows sliced off
    # decode-sequence boundaries (decode/engine.py): a sequence trace is
    # admit | queue | prefill | token* | settle — one token span per
    # generated token, so inter-token latency reads straight off /traces
    "joining": "queue",         # the decode loop claimed a KV slot
    "prefilled": "prefill",     # prompt prefill settled (first logits)
    "token": "token",           # one sampled token pushed to the stream
}

_SHED_REASON = {  # error type -> the reason stamped on terminal spans
    "RateLimited": "rate",
    "Overloaded": "queue",
    "RequestTimeout": "deadline",
    "EngineStopped": "stopped",
}

_DEFAULT_RING = 1024
_BATCH_RING = 512

_ring = collections.deque(maxlen=_DEFAULT_RING)
_batch_ring = collections.deque(maxlen=_BATCH_RING)
_lock = threading.Lock()
_ring_synced = [False]

_trace_ids = itertools.count(1)
_batch_ids = itertools.count(1)
_sample_seq = itertools.count(1)

_slo_lock = threading.Lock()
_slo_windows = {}    # (model, cls) -> deque[(monotonic_t, good)]
_slo_overrides = {}  # cls -> objective ms (programmatic, beats env)


def _reinit_after_fork():
    # same rationale as flight.py: a fork landing inside the critical
    # section would leave the lock held forever in the child
    global _lock, _slo_lock
    _lock = threading.Lock()
    _slo_lock = threading.Lock()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reinit_after_fork)


def _env_float(name, default):
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# sampling + the trace context
# ---------------------------------------------------------------------------


def sample_rate():
    """The head-based sample fraction from MXTPU_TRACE_SAMPLE, clamped
    to [0, 1]. 0 (default) = tracing fully off."""
    return min(1.0, max(0.0, _env_float("MXTPU_TRACE_SAMPLE", 0.0)))


def enabled():
    return sample_rate() > 0.0


def maybe_start(model, cls="interactive", rows=1, deadline=None):
    """Mint a :class:`ReqTrace` for this request, or None.

    The head-based sampling decision happens HERE, once, at submit:
    unsampled requests carry ``trace=None`` and every downstream hook
    is a single ``is None`` check. The sampler is a deterministic
    counter (request n is sampled iff ``floor(n*rate)`` advances), so a
    rate of 0.1 traces exactly every 10th request — no RNG, exact
    rates, reproducible runs."""
    rate = sample_rate()
    if rate <= 0.0:
        return None
    n = next(_sample_seq)
    if rate < 1.0 and int(n * rate) == int((n - 1) * rate):
        return None
    return ReqTrace(model, cls, rows, deadline)


def next_batch_id():
    """A fresh batch id for one assembled micro-batch (the causality
    link every member trace records)."""
    return next(_batch_ids)


class ReqTrace:
    """One sampled request's trace context: identity + boundary stamps.

    Mutated only by the engine pipeline (client thread at submit, the
    one assembler thread, the one completer thread — each boundary has
    exactly one writer); read only after :func:`finish` freezes it into
    the ring."""

    __slots__ = ("trace_id", "model", "cls", "rows", "deadline_ms",
                 "t_wall", "t0", "marks", "batch_id", "bucket", "extra")

    def __init__(self, model, cls, rows, deadline):
        self.trace_id = f"{os.getpid():x}-{next(_trace_ids):x}"
        self.model = str(model)
        self.cls = str(cls)
        self.rows = int(rows)
        self.deadline_ms = None if deadline is None else round(
            (deadline - time.monotonic()) * 1e3, 3)
        self.t_wall = time.time()
        self.t0 = time.perf_counter()
        self.marks = []          # [(boundary, perf_counter)]
        self.batch_id = None     # stamped by the assembler
        self.bucket = None
        self.extra = {}

    def stamp(self, boundary, t=None):
        """Close the current phase at ``t`` (a shared per-batch
        ``perf_counter`` read, or now)."""
        self.marks.append((boundary,
                           time.perf_counter() if t is None else t))

    def annotate(self, **fields):
        """Attach routing/context fields (FrontDoor stamps the chosen
        replica here)."""
        self.extra.update(fields)


# ---------------------------------------------------------------------------
# the finish chokepoint + rings
# ---------------------------------------------------------------------------


def finish(req, outcome, error=None):
    """The terminal chokepoint: called from ``ServeRequest._finish`` for
    EVERY settled outcome (ok / timeout / error / shed). Feeds the SLO
    window always; freezes the trace into the ring when the request was
    sampled. Never raises."""
    try:
        now = time.perf_counter()
        latency = time.monotonic() - req.t_submit
        # a request may nominate a different latency for its objective:
        # decode sequences set slo_latency_s to time-to-first-token, so
        # the class SLO judges responsiveness rather than penalizing
        # long (healthy) generations by their total wall time
        slo_latency = getattr(req, "slo_latency_s", None)
        slo_observe(getattr(req, "model", "") or "", req.cls, outcome,
                    latency if slo_latency is None else slo_latency)
        tr = getattr(req, "trace", None)
        if tr is None:
            return None
        reason = None
        if outcome != "ok":
            reason = _SHED_REASON.get(type(error).__name__,
                                      type(error).__name__
                                      if error is not None else outcome)
        spans, prev = [], tr.t0
        for boundary, t in tr.marks:
            spans.append({"phase": _PHASE_OF.get(boundary, boundary),
                          "t0": prev, "dur": t - prev})
            prev = t
        # terminal span: settle for served requests, the outcome (with
        # the shed reason) for everything dropped — contiguous with the
        # last boundary, so span durations still telescope to total
        spans.append({"phase": "settle" if outcome == "ok" else outcome,
                      "t0": prev, "dur": now - prev})
        rec = {
            "trace_id": tr.trace_id, "model": tr.model, "cls": tr.cls,
            "rows": tr.rows, "outcome": outcome, "reason": reason,
            "batch": tr.batch_id, "bucket": tr.bucket,
            "deadline_ms": tr.deadline_ms, "t_wall": tr.t_wall,
            "t0": tr.t0, "total_ms": (now - tr.t0) * 1e3,
            "spans": spans,
        }
        if tr.extra:
            rec["annotations"] = dict(tr.extra)
        _sync_ring()
        with _lock:
            _ring.append(rec)
        try:
            from ..telemetry import instruments as _instr

            _instr.record_serve_trace(tr.model, outcome)
        except Exception:
            pass
        return rec
    except Exception:
        return None


def record_batch(batch_id, model, traced, rows, bucket):
    """Freeze one completed micro-batch's shared span into the batch
    ring: the causality record linking ``batch_id`` to its member trace
    IDs, with the batch-wide assemble/dispatch/device phases (read off
    the first member's shared stamps). Never raises."""
    try:
        if not traced:
            return None
        stamps = dict(traced[0].marks)
        spans = []
        seq = [("assemble", "assembling", "dispatching"),
               ("dispatch", "dispatching", "dispatched"),
               ("device", "dispatched", "ready")]
        for phase, a, b in seq:
            if a in stamps and b in stamps:
                spans.append({"phase": phase, "t0": stamps[a],
                              "dur": stamps[b] - stamps[a]})
        rec = {
            "batch_id": batch_id, "model": str(model),
            "trace_ids": [tr.trace_id for tr in traced],
            "rows": int(rows), "bucket": int(bucket),
            "spans": spans,
        }
        with _lock:
            _batch_ring.append(rec)
        return rec
    except Exception:
        return None


def traces(n=None, cls=None, model=None):
    """Snapshot of finished request traces, oldest first, optionally
    filtered by class / model and trimmed to the newest ``n``."""
    with _lock:
        recs = list(_ring)
    if cls is not None:
        recs = [r for r in recs if r.get("cls") == str(cls)]
    if model is not None:
        recs = [r for r in recs if r.get("model") == str(model)]
    if n is not None:
        n = max(0, int(n))
        recs = recs[-n:] if n else []
    return recs


def batches(n=None):
    """Snapshot of batch causality records, oldest first."""
    with _lock:
        recs = list(_batch_ring)
    if n is not None:
        n = max(0, int(n))
        recs = recs[-n:] if n else []
    return recs


def phase_summary():
    """Per-phase aggregate over the ring: ``{phase: {avg_ms, n}}`` —
    the fleet-level "where do requests spend time" answer fleetctl
    renders per rank."""
    agg = {}
    for rec in traces():
        for sp in rec.get("spans", ()):
            a = agg.setdefault(sp["phase"], [0.0, 0])
            a[0] += sp["dur"]
            a[1] += 1
    return {ph: {"avg_ms": round(s / c * 1e3, 4), "n": c}
            for ph, (s, c) in sorted(agg.items())}


def ring_capacity():
    return _ring.maxlen


def set_ring_capacity(n):
    """Rebound the trace ring, keeping the newest records; returns the
    previous capacity."""
    global _ring
    n = max(1, int(n))
    _ring_synced[0] = True  # an explicit call beats the env default
    with _lock:
        prev = _ring.maxlen
        _ring = collections.deque(_ring, maxlen=n)
    return prev


def _sync_ring():
    # one-time: honor MXTPU_TRACE_RING without import-order games
    if _ring_synced[0]:
        return
    _ring_synced[0] = True
    raw = os.environ.get("MXTPU_TRACE_RING")
    try:
        n = int(raw) if raw else _DEFAULT_RING
    except ValueError:
        n = _DEFAULT_RING
    if n != _ring.maxlen:
        set_ring_capacity(n)


def reset():
    """Test hygiene: drop traces, batch links, SLO windows, overrides,
    and the sampling counter (so deterministic head-based sampling
    restarts from request 1); re-arm the ring-capacity env sync."""
    global _sample_seq
    with _lock:
        _ring.clear()
        _batch_ring.clear()
    with _slo_lock:
        _slo_windows.clear()
        _slo_overrides.clear()
    _sample_seq = itertools.count(1)
    _ring_synced[0] = False


# ---------------------------------------------------------------------------
# the SLO plane
# ---------------------------------------------------------------------------


def slo_objective_ms(cls):
    """The latency objective for a class, in ms: a programmatic
    override (:func:`set_slo_objective`) beats ``MXTPU_SLO_<CLASS>_MS``.
    0 = no objective declared — the class is not SLO-tracked."""
    ob = _slo_overrides.get(str(cls))
    if ob is not None:
        return float(ob)
    return _env_float(f"MXTPU_SLO_{str(cls).upper()}_MS", 0.0)


def set_slo_objective(cls, ms):
    """Declare (or with ``ms=None`` clear) a class objective
    programmatically."""
    with _slo_lock:
        if ms is None:
            _slo_overrides.pop(str(cls), None)
        else:
            _slo_overrides[str(cls)] = float(ms)


def _slo_target():
    return min(0.9999, max(0.0, _env_float("MXTPU_SLO_TARGET", 0.99)))


def _slo_window_s():
    return max(0.001, _env_float("MXTPU_SLO_WINDOW_S", 60.0))


def _trim_locked(win, now):
    horizon = now - _slo_window_s()
    while win and win[0][0] < horizon:
        win.popleft()


def _burn_locked(win):
    """Windowed bad fraction over the error budget (1 - target)."""
    total = len(win)
    if not total:
        return None, 0
    bad = sum(1 for _, good in win if not good)
    budget = 1.0 - _slo_target()
    return (bad / total) / budget, total


def slo_observe(model, cls, outcome, latency_s=None):
    """Fold one finished request into its class's rolling SLO window.

    Good iff the request was served within its class objective; shed /
    timeout / error outcomes are always bad. Classes with no declared
    objective are ignored (zero bookkeeping on the default config).
    Publishes the fresh burn rate to ``serve_slo_burn_rate``."""
    obj = slo_objective_ms(cls)
    if obj <= 0:
        return None
    good = (outcome == "ok" and latency_s is not None
            and latency_s * 1e3 <= obj)
    now = time.monotonic()
    with _slo_lock:
        win = _slo_windows.setdefault((str(model), str(cls)),
                                      collections.deque())
        win.append((now, good))
        _trim_locked(win, now)
        burn, _ = _burn_locked(win)
    try:
        from ..telemetry import instruments as _instr

        _instr.set_slo_burn(model, cls, burn or 0.0)
        if not good:
            _instr.record_slo_violation(
                model, cls, outcome if outcome != "ok" else "latency")
    except Exception:
        pass
    return burn


def slo_status():
    """Live SLO table: ``{model: {cls: {objective_ms, target, window_s,
    events, bad, burn, burning}}}``. Reads re-trim the windows, so a
    replica RECOVERS (burn decays to None) once the window rolls its
    violations off — even with no new traffic."""
    burn_max = _env_float("MXTPU_SLO_BURN_MAX", 1.0)
    min_events = int(_env_float("MXTPU_SLO_MIN_EVENTS", 10))
    now = time.monotonic()
    out = {}
    with _slo_lock:
        items = [(k, collections.deque(v)) for k, v in
                 _slo_windows.items()]
    for (model, cls), win in items:
        _trim_locked(win, now)
        burn, total = _burn_locked(win)
        bad = sum(1 for _, good in win if not good)
        out.setdefault(model, {})[cls] = {
            "objective_ms": slo_objective_ms(cls),
            "target": _slo_target(),
            "window_s": _slo_window_s(),
            "events": total,
            "bad": bad,
            "burn": None if burn is None else round(burn, 4),
            "burning": bool(burn is not None and total >= min_events
                            and burn > burn_max),
        }
        try:
            from ..telemetry import instruments as _instr

            _instr.set_slo_burn(model, cls, burn or 0.0)
        except Exception:
            pass
    return out


def slo_burning():
    """``{"model/cls": burn}`` for every class currently burning past
    MXTPU_SLO_BURN_MAX — the set that flips opsd ``/readyz`` to 503.
    Empty dict = every declared objective is healthy."""
    out = {}
    for model, classes in slo_status().items():
        for cls, st in classes.items():
            if st["burning"]:
                out[f"{model}/{cls}"] = st["burn"]
    return out
