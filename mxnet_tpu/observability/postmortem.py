"""Atomic postmortem bundles: one JSON file per rank with everything.

Every crash path converges here — the watchdog's stall dump, the
PreemptionHandler's emergency snapshot, the excepthook/atexit crash
hooks, a tripped numerics check, the periodic flight-recorder spill,
and an explicit ``observability.dump()``. The bundle is self-contained:

  * the flight-recorder event ring (flight.events()),
  * the telemetry dump (every counter/gauge/histogram),
  * the diagnostics span records + per-step phase table,
  * the compile registry (what XLA built, flops/peak-HBM per program),
  * numerics trips + bisect reports,
  * the typed env-var snapshot and process identity (job/rank/world).

Writes go through the ``_checkpoint_io`` engine path — serialized per
bundle path, committed with write-tmp → fsync → ``os.replace`` so a
kill mid-write leaves the previous complete bundle, never a torn one.
``sync=False`` queues the write on an engine IO thread (the periodic
spill never blocks training); crash paths use ``sync=True``.
``tools/blackbox.py`` merges N ranks' bundles into one chrome trace +
stall report.
"""
from __future__ import annotations

import json
import os
import sys
import time

__all__ = ["dump", "build_bundle", "default_path", "install_crash_hooks",
           "crash_hooks_installed"]

BUNDLE_FORMAT = 1

_hooks = {"installed": False, "prev_excepthook": None, "fh_file": None}


def _jsonable(x):
    try:
        return float(x)
    except (TypeError, ValueError):
        return str(x)


def default_path(rank=None):
    """``<MXTPU_FLIGHTREC_DIR>/mxtpu_blackbox.rank<r>.json``."""
    from . import flight

    try:
        from .. import env as _env

        d = _env.get("MXTPU_FLIGHTREC_DIR") \
            if "MXTPU_FLIGHTREC_DIR" in _env.all_vars() else "."
    except Exception:
        d = os.environ.get("MXTPU_FLIGHTREC_DIR", ".")
    d = d or "."
    if rank is None:
        rank = flight.identity()["rank"]
    return os.path.join(d, f"mxtpu_blackbox.rank{rank}.json")


def build_bundle(reason, extra=None):
    """Assemble the bundle dict. Each section is independently guarded:
    a half-dead process must still produce SOME bundle."""
    from . import flight, numerics

    bundle = {
        "format": BUNDLE_FORMAT,
        "reason": str(reason),
        "time": time.time(),
        "pid": os.getpid(),
        "identity": flight.identity(),
        "events": flight.events(),
        "numerics_trips": numerics.trips(),
    }
    try:
        from .. import env as _env

        bundle["env"] = {name: _jsonable(var.read())
                         for name, var in _env.all_vars().items()}
    except Exception as e:
        bundle["env"] = {"error": repr(e)}
    try:
        from .. import telemetry

        bundle["telemetry"] = telemetry.dump()
    except Exception as e:
        bundle["telemetry"] = {"error": repr(e)}
    try:
        from ..diagnostics import spans as _spans

        bundle["spans"] = _spans.records()
        bundle["step_table"] = {
            str(k): v for k, v in _spans.step_table().items()}
        bundle["trace_context"] = _spans.trace_context()
    except Exception as e:
        bundle["spans"] = []
        bundle["step_table"] = {"error": repr(e)}
    try:
        from ..diagnostics import introspect as _introspect

        bundle["compile_registry"] = {
            f"{b}/{v}": entry
            for (b, v), entry in _introspect.compile_registry().items()}
    except Exception as e:
        bundle["compile_registry"] = {"error": repr(e)}
    try:
        from ..diagnostics import watchdog as _watchdog

        bundle["watchdog_dump"] = _watchdog.last_dump()
    except Exception:
        bundle["watchdog_dump"] = None
    try:
        from . import reqtrace

        # request traces + batch links + SLO table ride in the bundle so
        # tools/blackbox.py can interleave per-request spans with rank
        # spans in the merged chrome trace
        bundle["req_traces"] = reqtrace.traces()
        bundle["req_batches"] = reqtrace.batches()
        bundle["slo"] = reqtrace.slo_status()
    except Exception as e:
        bundle["req_traces"] = []
        bundle["req_batches"] = []
        bundle["slo"] = {"error": repr(e)}
    try:
        from . import costdb as _costdb
        from . import measure as _measure

        # the in-memory measurement cache + drift join ride along so a
        # crash still carries what was measured and how far the byte
        # model had drifted
        d = _costdb.db()
        bundle["costdb"] = {
            "path": d.path,
            "entries": d.entries(),
            "drift": _costdb.drift_report(),
            "pending": _measure.pending(),
            "site_scores": _measure.site_scores(),
        }
    except Exception as e:
        bundle["costdb"] = {"error": repr(e)}
    if extra:
        bundle.update(extra)
    return bundle


def _atomic_write(path, payload):
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def dump(reason="manual", path=None, sync=True, extra=None):
    """Serialize the bundle to ``path`` (default: the per-rank blackbox
    file) through the _checkpoint_io atomic-commit path. Returns the
    bundle path. Never raises on the async path; the sync path raises
    only when even the direct-write fallback fails."""
    if path is None:
        path = default_path()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = json.dumps(build_bundle(reason, extra), default=_jsonable)
    try:
        from ..telemetry import instruments as _instr

        _instr.record_postmortem(str(reason).split(":", 1)[0])
    except Exception:
        pass
    try:
        from .. import _checkpoint_io

        _checkpoint_io.async_run(path, lambda: _atomic_write(path, payload))
        if sync:
            _checkpoint_io.wait_for_path(path)
    except Exception:
        # engine gone (atexit/teardown) or the queued write failed:
        # last-ditch direct write, still atomic
        if sync:
            _atomic_write(path, payload)
        else:
            try:
                _atomic_write(path, payload)
            except Exception:
                pass
    return path


# ---------------------------------------------------------------------------
# crash hooks
# ---------------------------------------------------------------------------


def crash_hooks_installed():
    return _hooks["installed"]


def install_crash_hooks():
    """Arm the crash paths (idempotent):

      * ``sys.excepthook`` — an uncaught exception records a ``crash``
        flight event and writes the bundle before the interpreter dies;
      * ``atexit`` — a final bundle on interpreter shutdown (reason
        ``exit``), so even clean exits leave the black box behind;
      * ``faulthandler`` — hard faults (SIGSEGV/SIGABRT) dump native
        tracebacks next to the bundle (Python can't run there, so this
        is a text sidecar, not a JSON bundle).

    Auto-armed at import when ``MXTPU_FLIGHTREC_CRASHDUMP=1``.
    """
    if _hooks["installed"]:
        return False
    _hooks["installed"] = True

    import atexit

    from . import flight

    prev = sys.excepthook
    _hooks["prev_excepthook"] = prev

    def hook(exc_type, exc, tb):
        try:
            flight.record("crash", error=f"{exc_type.__name__}: {exc}")
            dump(reason=f"crash:{exc_type.__name__}", sync=True)
            _hooks["crash_dumped"] = True
        except Exception:
            pass
        prev(exc_type, exc, tb)

    sys.excepthook = hook

    def on_exit():
        if _hooks.get("crash_dumped"):
            return  # don't overwrite the crash bundle with reason "exit"
        try:
            dump(reason="exit", sync=True)
        except Exception:
            pass

    atexit.register(on_exit)

    try:
        import faulthandler

        rank = flight.identity()["rank"]
        side = os.path.join(
            os.path.dirname(default_path()) or ".",
            f"mxtpu_faulthandler.rank{rank}.txt")
        f = open(side, "w")  # noqa: SIM115 — must outlive this frame
        _hooks["fh_file"] = f
        faulthandler.enable(file=f)
    except Exception:
        pass
    return True
