"""Live ops server: the per-rank HTTP plane supervisors actually poll.

Eight PRs of in-process instrumentation (telemetry registry, diagnostics
spans, flight recorder, postmortem bundles) were all dump-to-file and
post-hoc. ``opsd`` turns them into a live, per-process control/metrics
plane — the thing a load balancer health-checks, a Prometheus scrapes,
and an elastic-training supervisor polls (docs/observability.md §5;
the TensorFlow paper's long-running training/serving-fleet framing):

  GET  /metrics          Prometheus scrape of the telemetry registry
  GET  /healthz          liveness: the process (and its ops thread) is up
  GET  /readyz           readiness: no ongoing watchdog stall, every
                         registered serving engine admitting (503 + the
                         failing checks otherwise)
  GET  /flight?n=N       live flight-ring tail as JSON (newest N);
                         &kind=PREFIX filters by event-kind prefix
                         (kind=serve pulls only serving events)
  GET  /traces?n=N       newest N finished request traces (reqtrace.py:
                         phase spans, batch links, SLO table, per-phase
                         summary); &class= / &model= filter
  GET  /costdb?n=N       measurement-plane view: CostDB summary, the
                         drift auditor's predicted-vs-measured join,
                         tripped programs, newest N entries
  GET  /steps            step-tracer phase table + last-step/step-rate
  GET  /identity         (job_id, rank, world) + pid/host/port — stamped
                         by kvstore.tpu_dist at collective init
  POST /postmortem       write a postmortem bundle NOW, return its path
  POST /profile?ms=N     capture a jax.profiler trace for N ms, return
                         the trace directory

Opt-in and cheap: with ``MXTPU_OPS_PORT`` unset no thread or socket is
ever created; with it set, one stdlib ``ThreadingHTTPServer`` runs on a
daemon thread named ``mxtpu-opsd`` (exempt from the DataLoader fork
heuristic like every framework service thread). GET handlers only read
snapshot APIs that already exist for postmortems — they take no jax
locks and never touch the device, so a 10 Hz scraper cannot retrace,
stall, or perturb a donated whole-step training loop. The POST
endpoints mutate (bundle writes, profiler sessions) and can be gated
with ``MXTPU_OPS_TOKEN`` (bearer token).

Fleet view: ``tools/fleetctl.py`` polls N ranks' servers into one
straggler-annotated table and can fan ``POST /postmortem`` out to every
rank for a ``tools/blackbox.py`` merge.

Fork/exit safety: an ``os.fork`` child (DataLoader workers) inherits
the listening socket fd but not the server thread — the at-fork hook
closes the child's fd and clears the singleton so the child neither
holds the port nor believes a server runs. ``atexit`` stops the server
on interpreter shutdown so the port is released before teardown.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

__all__ = ["OpsServer", "start", "stop", "server", "start_from_env"]

_singleton = [None]   # the env-started per-process server
_lock = threading.Lock()

_PROFILE_MAX_MS = 60_000


def _env_get(name, default):
    try:
        from .. import env as _env

        if name in _env.all_vars():
            return _env.get(name)
    except Exception:
        pass
    raw = os.environ.get(name)
    return default if raw is None else raw


# ---------------------------------------------------------------------------
# endpoint payload builders (pure snapshot reads; shared with tests)
# ---------------------------------------------------------------------------


def health_payload():
    """Liveness: the process is up and its Python side can answer."""
    from ..diagnostics import spans as _spans
    from . import flight as _flight

    return {
        "status": "ok",
        "pid": os.getpid(),
        "time": time.time(),
        "step": _spans.current_step(),
        "identity": _flight.identity(),
    }


def readiness_payload():
    """Readiness checks: ``ready`` is False while a watchdog guard has
    fired and is still open (an ongoing stall) or while any registered
    serving engine would shed/refuse a submit right now. Engines are
    read from ``serving.REGISTRY`` — register yours there to have the
    front door health-check it."""
    checks = {}
    ready = True
    try:
        from ..diagnostics import watchdog as _watchdog

        stalled = _watchdog.stalled_sites()
        checks["watchdog"] = {
            "ok": not stalled,
            "stalled_sites": stalled,
            "fire_count": _watchdog.fire_count(),
        }
        ready &= not stalled
    except Exception as e:
        checks["watchdog"] = {"ok": True, "error": repr(e)}
    try:
        import sys

        serving = sys.modules.get("mxnet_tpu.serving")
        engines = {}
        if serving is not None:
            for name in serving.REGISTRY.names():
                eng = serving.REGISTRY.get(name)
                state = eng.admission_state()
                engines[name] = {
                    "admission": state,
                    "queue_depth": eng.queue_depth(),
                    "max_queue": eng.max_queue,
                    "started": eng.started,
                }
                ready &= state == "ok"
        checks["serving"] = {
            "ok": all(e["admission"] == "ok" for e in engines.values()),
            "engines": engines,
        }
    except Exception as e:
        checks["serving"] = {"ok": True, "error": repr(e)}
    try:
        from . import reqtrace

        # a class burning through its error budget drops this replica
        # from rotation (front doors poll /readyz); recovery is
        # automatic once the rolling window sheds the violations
        burning = reqtrace.slo_burning()
        checks["slo"] = {
            "ok": not burning,
            "burning": burning,
            "status": reqtrace.slo_status(),
        }
        ready &= not burning
    except Exception as e:
        checks["slo"] = {"ok": True, "error": repr(e)}
    return {"ready": bool(ready), "checks": checks}


def steps_payload():
    """The step tracer's live view: per-step phase table, last step, and
    the step-rate gauges a fleet poller derives straggler skew from."""
    from ..diagnostics import spans as _spans

    out = {
        "last_step": _spans.current_step(),
        "step_table": {str(k): v for k, v in _spans.step_table().items()},
    }
    try:
        from ..telemetry import instruments as ti

        st = ti.step_time_seconds
        out["steps_observed"] = st.count
        out["step_time_ms_avg"] = \
            round(st.sum / st.count * 1e3, 3) if st.count else None
        out["examples_per_second"] = ti.examples_per_second.value
        out["step_dispatches"] = {
            lv[0]: c.value for lv, c in ti.step_dispatch_total.series()}
    except Exception as e:
        out["telemetry_error"] = repr(e)
    return out


def identity_payload(srv=None):
    from . import flight as _flight

    out = dict(_flight.identity())
    out["pid"] = os.getpid()
    if srv is not None:
        out["host"], out["port"] = srv.host, srv.port
        out["started_at"] = srv.started_at
    return out


def flight_payload(n=256, kind=None):
    from . import flight as _flight

    evs = _flight.events(kind=kind)
    n = max(0, int(n))
    return {
        "identity": _flight.identity(),
        "capacity": _flight.capacity(),
        "kind": kind,
        "total": len(evs),
        "events": evs[-n:] if n else [],
    }


def costdb_payload(n=64):
    """The measurement plane's live view: CostDB summary + the drift
    auditor's join (calibration, per-program ratios, tripped programs)
    + the newest ``n`` raw entries. ``n=0`` keeps just the summary —
    what fleetctl polls per rank for its drift column."""
    from . import costdb as _costdb
    from . import flight as _flight
    from . import measure as _measure

    d = _costdb.db()
    rep = _costdb.audit()
    entries = d.entries()
    n = max(0, int(n))
    return {
        "identity": _flight.identity(),
        "mode": _measure.mode(),
        "path": d.path,
        "total": len(entries),
        "platforms": sorted({str(e.get("platform")) for e in entries}),
        "threshold": rep.get("threshold"),
        "calibration": rep.get("calibration"),
        "drift": rep.get("programs"),
        "tripped": rep.get("tripped"),
        "pending": _measure.pending(),
        "site_scores": _measure.site_scores(),
        "entries": entries[-n:] if n else [],
    }


def traces_payload(n=32, cls=None, model=None):
    """Finished request traces + batch causality links + the live SLO
    table and per-phase latency breakdown (reqtrace.py). ``n=0`` keeps
    just the summaries — what fleetctl polls per rank."""
    from . import flight as _flight
    from . import reqtrace

    recs = reqtrace.traces(cls=cls, model=model)
    n = max(0, int(n))
    # decode-sequence traces carry per-token spans; summarize them so
    # TTFT / inter-token behavior reads off /traces without digging
    # through span lists (decode/engine.py stamps prefill + token)
    decode = {"sequences": 0, "tokens": 0}
    ttfts = []
    for r in recs:
        spans = r.get("spans", ())
        toks = sum(1 for sp in spans if sp["phase"] == "token")
        if not toks:
            continue
        decode["sequences"] += 1
        decode["tokens"] += toks
        for sp in spans:
            if sp["phase"] == "token":
                # first token span closes at its stamp: TTFT = t0 + dur
                # relative to trace start
                ttfts.append((sp["t0"] + sp["dur"] - r["t0"]) * 1e3)
                break
    if ttfts:
        ttfts.sort()
        decode["ttft_p50_ms"] = round(ttfts[len(ttfts) // 2], 3)
        decode["ttft_max_ms"] = round(ttfts[-1], 3)
    return {
        "identity": _flight.identity(),
        "sample_rate": reqtrace.sample_rate(),
        "capacity": reqtrace.ring_capacity(),
        "class": cls,
        "model": model,
        "total": len(recs),
        "traces": recs[-n:] if n else [],
        "batches": reqtrace.batches(n),
        "phases": reqtrace.phase_summary(),
        "decode": decode,
        "slo": reqtrace.slo_status(),
    }


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "mxtpu-opsd"

    # BaseHTTPRequestHandler logs every request to stderr; a 10 Hz
    # scraper would bury real output
    def log_message(self, fmt, *args):  # noqa: ARG002
        pass

    @property
    def ops(self):
        return self.server._ops  # the owning OpsServer

    def _send(self, code, body, content_type="application/json"):
        if isinstance(body, (dict, list)):
            body = json.dumps(body, default=str)
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _authorized(self):
        token = str(_env_get("MXTPU_OPS_TOKEN", "") or "")
        if not token:
            return True
        got = self.headers.get("Authorization", "")
        return got == f"Bearer {token}"

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            if url.path == "/metrics":
                from ..telemetry import prometheus_text
                from ..telemetry.promparse import CONTENT_TYPE

                self._send(200, prometheus_text(),
                           content_type=CONTENT_TYPE)
            elif url.path == "/healthz":
                self._send(200, health_payload())
            elif url.path == "/readyz":
                p = readiness_payload()
                self._send(200 if p["ready"] else 503, p)
            elif url.path == "/steps":
                self._send(200, steps_payload())
            elif url.path == "/identity":
                self._send(200, identity_payload(self.ops))
            elif url.path == "/flight":
                n = int(q.get("n", ["256"])[0])
                kind = q.get("kind", [None])[0]
                self._send(200, flight_payload(n, kind=kind))
            elif url.path == "/traces":
                n = int(q.get("n", ["32"])[0])
                cls = q.get("class", [None])[0]
                model = q.get("model", [None])[0]
                self._send(200, traces_payload(n, cls=cls, model=model))
            elif url.path == "/costdb":
                n = int(q.get("n", ["64"])[0])
                self._send(200, costdb_payload(n))
            elif url.path == "/":
                self._send(200, {
                    "server": "mxtpu-opsd",
                    "endpoints": ["/metrics", "/healthz", "/readyz",
                                  "/steps", "/identity", "/flight",
                                  "/traces", "/costdb",
                                  "POST /postmortem", "POST /profile"],
                })
            else:
                self._send(404, {"error": f"no endpoint {url.path!r}"})
        except Exception as e:  # a broken section must answer, not hang
            self._send(500, {"error": repr(e)})

    def do_POST(self):  # noqa: N802
        url = urlparse(self.path)
        q = parse_qs(url.query)
        if not self._authorized():
            self._send(401, {"error": "MXTPU_OPS_TOKEN required "
                                      "(Authorization: Bearer <token>)"})
            return
        try:
            if url.path == "/postmortem":
                from . import postmortem

                path = postmortem.dump(reason="opsd", sync=True)
                self._send(200, {"path": os.path.abspath(path)})
            elif url.path == "/profile":
                ms = float(q.get("ms", ["1000"])[0])
                self._send(200, self.ops.capture_profile(ms))
            else:
                self._send(404, {"error": f"no endpoint {url.path!r}"})
        except Exception as e:
            self._send(500, {"error": repr(e)})


class OpsServer:
    """One live ops endpoint: a ThreadingHTTPServer on a daemon thread.

    ``port=0`` binds an ephemeral port (tests, multi-engine bring-up);
    the bound port is ``self.port``. The server is independent of the
    module singleton, so a front-door process can run several.
    """

    def __init__(self, port=None, host=None):
        if port is None:
            port = int(_env_get("MXTPU_OPS_PORT", 0) or 0)
        if host is None:
            host = str(_env_get("MXTPU_OPS_HOST", "127.0.0.1")
                       or "127.0.0.1")
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd._ops = self
        self.host, self.port = self._httpd.server_address[:2]
        self.started_at = time.time()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="mxtpu-opsd",
            daemon=True, kwargs={"poll_interval": 0.1})
        self._profile_lock = threading.Lock()
        self._stopped = False

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        self._thread.start()
        try:
            from . import flight

            flight.record("opsd_start", host=self.host, port=self.port)
        except Exception:
            pass
        return self

    @property
    def running(self):
        return self._thread.is_alive() and not self._stopped

    def stop(self):
        """Shut the listener down and release the port (idempotent)."""
        if self._stopped:
            return self
        self._stopped = True
        try:
            self._httpd.shutdown()
        except Exception:
            pass
        try:
            self._httpd.server_close()
        except Exception:
            pass
        if self._thread.is_alive() and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
        try:
            from . import flight

            flight.record("opsd_stop", port=self.port)
        except Exception:
            pass
        return self

    def _close_inherited_socket(self):
        # after os.fork the CHILD holds a copy of the listening fd but
        # no server thread; close the copy so the child doesn't keep the
        # port open (the parent's listener is unaffected)
        self._stopped = True
        try:
            self._httpd.socket.close()
        except Exception:
            pass

    def capture_profile(self, ms):
        """On-demand ``jax.profiler`` capture: trace for ``ms`` wall
        milliseconds into a fresh directory under MXTPU_FLIGHTREC_DIR,
        return ``{"dir", "ms"}``. One capture at a time — overlapping
        requests get 409-shaped errors rather than corrupt traces."""
        ms = max(1.0, min(float(ms), float(_PROFILE_MAX_MS)))
        if not self._profile_lock.acquire(blocking=False):
            raise RuntimeError("a profile capture is already running")
        try:
            import jax

            base = str(_env_get("MXTPU_FLIGHTREC_DIR", ".") or ".")
            out = os.path.join(
                base, f"opsd_profile_{int(time.time() * 1e3)}")
            os.makedirs(out, exist_ok=True)
            jax.profiler.start_trace(out)
            try:
                time.sleep(ms / 1e3)
            finally:
                jax.profiler.stop_trace()
            try:
                from . import flight

                flight.record("opsd_profile", dir=out, ms=ms)
            except Exception:
                pass
            return {"dir": os.path.abspath(out), "ms": ms}
        finally:
            self._profile_lock.release()


# ---------------------------------------------------------------------------
# per-process singleton (the MXTPU_OPS_PORT path)
# ---------------------------------------------------------------------------


def server():
    """The env-started per-process server, or None."""
    return _singleton[0]


def start(port=None, host=None):
    """Start (or return) the per-process ops server. Idempotent; the
    first call wins the port. Registers the atexit stop."""
    with _lock:
        srv = _singleton[0]
        if srv is not None and srv.running:
            return srv
        srv = OpsServer(port=port, host=host).start()
        _singleton[0] = srv

        import atexit

        atexit.register(_atexit_stop)
        return srv


def stop():
    """Stop the per-process server (no-op when none runs)."""
    with _lock:
        srv = _singleton[0]
        _singleton[0] = None
    if srv is not None:
        srv.stop()
    return srv


def _atexit_stop():
    try:
        stop()
    except Exception:
        pass


def start_from_env():
    """The import-time hook: start iff ``MXTPU_OPS_PORT`` is set and
    non-zero. With it unset this touches nothing — no thread, no
    socket, no jax import."""
    raw = os.environ.get("MXTPU_OPS_PORT")
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    if port <= 0:
        return None
    try:
        return start(port=port)
    except OSError:
        # the port is taken (a sibling rank on the same host, a stale
        # process) — a dead ops plane must never kill training
        return None


def _after_fork_in_child():
    srv = _singleton[0]
    _singleton[0] = None
    if srv is not None:
        srv._close_inherited_socket()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_after_fork_in_child)
