"""On-device program measurement harness (the profiler half of the
measurement plane; observability/costdb.py is the persistence half).

Every compiled program already passes through
``diagnostics.introspect.capture_compile`` — CachedOp variants, the
whole-step program, fused-optimizer updates. This module hooks that
seam: under ``MXTPU_MEASURE=on_compile`` each registration runs a
warmed, synchronized wall-clock microbenchmark of the jitted callable
on the live device and records ``{fingerprint, platform, wall_ms
p50/p95, peak_bytes if available, arg shapes/dtypes, analytic
predictions, kernel-dispatch site scores, telemetry snapshot}`` into
the CostDB. ``MXTPU_MEASURE=cli`` instead stashes the callables for a
deferred :func:`sweep` (what ``tools/costdb.py measure`` drives), and
the default ``off`` returns before touching jax — default runs stay
bitwise-identical with zero extra jit traces and zero extra device
dispatches (same kill-switch contract as ``MXTPU_KERNELS=off``).

Mechanics worth knowing:

  * registration converts large array leaves (> ``SMALL_LEAF_BYTES``)
    to ``ShapeDtypeStruct`` so the pending cache never pins real
    weights; measurement materializes fresh zero buffers per timed run
    because donated programs (``donate_argnums``) invalidate their
    inputs — re-passing run 1's buffers would crash run 2;
  * the fingerprint is the PR-7 dedup ``structural_key`` (sha1-packed,
    address tokens scrubbed so it is stable across processes), falling
    back to a digest of the printed jaxpr when the program is
    unhashable;
  * the analytic predictions come from ``passes/memory.py``
    (``estimate_region_bytes`` / ``estimate_peak_bytes``) over a
    re-trace wrapped in ``suppress_trace_bumps`` so measurement never
    perturbs the zero-retrace telemetry proofs;
  * kernel dispatch (``kernels/dispatch.record``) reports each site's
    analytic XLA-vs-kernel byte scores to :func:`note_site`; the
    snapshot current at registration rides into the entry so the drift
    auditor can join program-level measurements against the BN-kernel
    and fused-optimizer decisions made inside that program.
"""
from __future__ import annotations

import hashlib
import os
import re
import threading
import time

__all__ = [
    "mode", "enabled", "maybe_register", "pending", "sweep",
    "measure_callable", "note_site", "site_scores", "fingerprint_of",
    "reset", "SMALL_LEAF_BYTES",
]

# args-cache leaves bigger than this become ShapeDtypeStructs at
# registration (don't pin weights); small leaves (PRNG keys, scalars)
# stay concrete so extended dtypes need no zero-materialization
SMALL_LEAF_BYTES = 4096

_MODES = {
    "off": "off", "": "off", "0": "off", "false": "off", "no": "off",
    "on_compile": "on_compile", "on-compile": "on_compile",
    "compile": "on_compile", "on": "on_compile", "1": "on_compile",
    "true": "on_compile",
    "cli": "cli", "defer": "cli", "deferred": "cli",
}

_tls = threading.local()
_lock = threading.Lock()
_pending = {}      # (block, variant) -> {"fn", "args", "kwargs", "sites"}
_SITE_SCORES = {}  # kernel -> latest {"site", outcome, bytes, ...}


def _env_get(name, default):
    try:
        from .. import env as _env

        if name in _env.all_vars():
            return _env.get(name)
    except Exception:
        pass
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return type(default)(raw)
    except (TypeError, ValueError):
        return default


def mode():
    """``off`` | ``on_compile`` | ``cli`` (unknown values read as
    off — an observability knob must fail closed, not crash or
    measure)."""
    raw = str(_env_get("MXTPU_MEASURE", "off") or "off").strip().lower()
    return _MODES.get(raw, "off")


def enabled():
    return mode() != "off"


# ---------------------------------------------------------------------------
# kernel-dispatch site scores
# ---------------------------------------------------------------------------


def note_site(kernel, outcome, xla_bytes=None, kernel_bytes=None,
              bytes_saved=0):
    """Called by ``kernels/dispatch.record`` with the analytic scores
    behind one dispatch decision. Always cheap (dict store); kept even
    when measurement is off so turning measurement on later still has
    the latest scores to join against."""
    score = {
        "site": str(kernel), "outcome": str(outcome),
        "xla_bytes": None if xla_bytes is None else int(xla_bytes),
        "kernel_bytes": None if kernel_bytes is None
        else int(kernel_bytes),
        "bytes_saved": int(bytes_saved or 0),
    }
    with _lock:
        _SITE_SCORES[score["site"]] = score
    sink = getattr(_tls, "site_sink", None)
    if sink is not None:
        sink.append(score)


def site_scores():
    """Latest analytic score per kernel-dispatch site."""
    with _lock:
        return {k: dict(v) for k, v in _SITE_SCORES.items()}


# ---------------------------------------------------------------------------
# registration (the capture_compile hook)
# ---------------------------------------------------------------------------


def _to_spec(tree):
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype") \
                and not isinstance(x, jax.ShapeDtypeStruct):
            try:
                if jax.dtypes.issubdtype(x.dtype, jax.dtypes.extended):
                    return x  # typed PRNG keys etc: keep concrete
            except Exception:
                pass
            if int(getattr(x, "nbytes", 0) or 0) > SMALL_LEAF_BYTES:
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x
    return jax.tree_util.tree_map(leaf, tree)


def _materialize(tree):
    """Fresh device buffers for every array leaf: zeros for specs, a
    copy for concrete leaves. The stored tree itself is NEVER passed to
    the program — donated buffers are invalidated by the run."""
    import jax
    import jax.numpy as jnp

    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return jnp.zeros(x.shape, x.dtype)
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            try:
                return jnp.array(x)  # copies: fresh, donate-safe buffer
            except Exception:
                return x
        return x
    return jax.tree_util.tree_map(leaf, tree)


def maybe_register(block, variant, jitted, args, kwargs=None):
    """The ``capture_compile`` hook. Never raises; the first check is a
    plain env read so ``MXTPU_MEASURE`` unset/off costs one dict lookup
    and touches no jax state."""
    if mode() == "off":
        return None
    if getattr(_tls, "busy", False):
        return None  # measurement re-entered capture_compile
    try:
        spec_args = _to_spec(tuple(args))
        spec_kwargs = _to_spec(dict(kwargs or {}))
        sites = list(site_scores().values())
        if mode() == "cli":
            with _lock:
                _pending[(str(block), str(variant))] = {
                    "fn": jitted, "args": spec_args,
                    "kwargs": spec_kwargs, "sites": sites,
                }
            return None
        return measure_callable(jitted, spec_args, block=block,
                                variant=variant, kwargs=spec_kwargs,
                                sites=sites)
    except Exception:
        return None


def pending():
    """Programs stashed under ``MXTPU_MEASURE=cli`` awaiting
    :func:`sweep`, as ``["block/variant", ...]``."""
    with _lock:
        return sorted(f"{b}/{v}" for b, v in _pending)


def sweep():
    """Measure every stashed program (cli mode); returns the list of
    CostDB entries. Failures skip that program, never abort the
    sweep."""
    with _lock:
        work = list(_pending.items())
        _pending.clear()
    out = []
    for (block, variant), rec in work:
        try:
            entry = measure_callable(
                rec["fn"], rec["args"], block=block, variant=variant,
                kwargs=rec["kwargs"], sites=rec["sites"])
        except Exception:
            entry = None
        if entry is not None:
            out.append(entry)
    return out


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------


def fingerprint_of(closed):
    """Stable program identity: sha1 of the PR-7 dedup structural key
    (identity-hash address tokens scrubbed so the digest survives
    process boundaries), else of the printed jaxpr."""
    text = None
    try:
        from ..passes import dedup as _dedup

        key = _dedup.structural_key(closed)
        if key is not None:
            text = repr(key)
    except Exception:
        pass
    if text is None:
        text = str(getattr(closed, "jaxpr", closed))
    text = re.sub(r"0x[0-9a-fA-F]+", "0x", text)
    return hashlib.sha1(text.encode()).hexdigest()[:16]


def _leaf_summary(tree, cap=32):
    import jax

    names = []
    for x in jax.tree_util.tree_leaves(tree):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            shape = ",".join(str(d) for d in x.shape)
            names.append(f"{x.dtype}[{shape}]")
        else:
            names.append(type(x).__name__)
    more = len(names) - cap
    return names[:cap] + ([f"...+{more}"] if more > 0 else [])


def _telemetry_snapshot():
    keep = ("jit_trace_total", "kernel_dispatch_total")
    try:
        from ..telemetry import exporters as _exp

        dumped = _exp.dump()
        return {k: dumped[k] for k in keep if k in dumped}
    except Exception:
        return {}


def _peak_device_bytes():
    try:
        import jax

        peaks = []
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats and stats.get("peak_bytes_in_use"):
                peaks.append(int(stats["peak_bytes_in_use"]))
        return max(peaks) if peaks else None
    except Exception:
        return None


def _percentile(sorted_ms, q):
    if not sorted_ms:
        return None
    i = min(len(sorted_ms) - 1,
            max(0, int(round(q * (len(sorted_ms) - 1)))))
    return sorted_ms[i]


def measure_callable(fn, args, block="?", variant="?", kwargs=None,
                     sites=None):
    """Run the warmed, synchronized microbenchmark of ``fn(*args,
    **kwargs)`` and record the CostDB entry. Returns the entry dict, or
    None when the program can't be materialized on this backend."""
    import jax

    kwargs = dict(kwargs or {})
    runs = max(1, int(_env_get("MXTPU_MEASURE_RUNS", 5)))
    warmup = max(0, int(_env_get("MXTPU_MEASURE_WARMUP", 1)))
    _tls.busy = True
    try:
        try:
            mat_args = _materialize(args)
            mat_kwargs = _materialize(kwargs)
        except Exception:
            return None

        # identity + analytic predictions from one suppressed re-trace
        # (trace caches make this cheap when the program is warm; the
        # suppression keeps zero-retrace telemetry proofs honest). The
        # site sink stays active through the warmup/timed runs too:
        # whichever call first traces the program for real is where the
        # dispatch decisions — note_site — actually fire.
        fingerprint = None
        predicted_bytes = predicted_peak = None
        collected = []
        _tls.site_sink = collected
        try:
            try:
                from ..passes import _state as _pstate

                with _pstate.suppress_trace_bumps():
                    closed = jax.make_jaxpr(
                        lambda *a: fn(*a, **mat_kwargs))(*mat_args)
                fingerprint = fingerprint_of(closed)
                from ..passes import memory as _memory

                regions = _memory.estimate_region_bytes(closed)
                predicted_bytes = sum(
                    int(r.get("external_bytes", 0) or 0) for r in regions)
                predicted_peak = int(_memory.estimate_peak_bytes(closed))
            except Exception:
                pass
            if fingerprint is None:
                fingerprint = hashlib.sha1(
                    f"{block}/{variant}".encode()).hexdigest()[:16]
            if not predicted_bytes:
                # degenerate programs: price the visible I/O so the
                # drift join has a nonzero denominator
                predicted_bytes = sum(
                    int(getattr(x, "nbytes", 0) or 0)
                    for x in jax.tree_util.tree_leaves((mat_args,
                                                        mat_kwargs)))

            for _ in range(warmup):
                out = fn(*_materialize(args), **_materialize(kwargs))
                jax.block_until_ready(out)
            times_ms = []
            for _ in range(runs):
                a = _materialize(args)
                k = _materialize(kwargs)
                jax.block_until_ready((a, k))  # zeros before the clock
                t0 = time.perf_counter()
                out = fn(*a, **k)
                jax.block_until_ready(out)
                times_ms.append((time.perf_counter() - t0) * 1000.0)
            times_ms.sort()
        finally:
            _tls.site_sink = None

        platform = jax.default_backend()
        entry = {
            "fingerprint": fingerprint,
            "platform": str(platform),
            "block": str(block),
            "variant": str(variant),
            "wall_ms_p50": _percentile(times_ms, 0.50),
            "wall_ms_p95": _percentile(times_ms, 0.95),
            "runs": runs,
            "warmup": warmup,
            "peak_bytes": _peak_device_bytes(),
            "predicted_bytes": predicted_bytes,
            "predicted_peak_bytes": predicted_peak,
            "args": _leaf_summary((args, kwargs)),
            # pjit caching makes the re-trace's sink see only the sites
            # that actually re-ran; the registration snapshot fills in
            # the rest, sink scores winning where both saw a site
            "sites": list({
                **{s["site"]: s for s in (sites or [])},
                **{s["site"]: s for s in collected},
            }.values()),
            "telemetry": _telemetry_snapshot(),
            "time": time.time(),
        }
        from . import costdb as _costdb

        entry = _costdb.db().put(entry)
        try:
            from ..telemetry import instruments as _instr

            _instr.record_cost_measure(block, variant,
                                       wall_ms=entry["wall_ms_p50"])
        except Exception:
            pass
        _costdb.audit()
        return entry
    finally:
        _tls.busy = False


def reset():
    """Test hygiene: drop pending programs + site scores."""
    with _lock:
        _pending.clear()
        _SITE_SCORES.clear()
    _tls.busy = False
    _tls.site_sink = None
