"""Training callbacks (reference: python/mxnet/callback.py).

Used with the estimator/fit loops: epoch-end checkpointing, periodic metric
logging, throughput reporting. Callbacks receive a BatchEndParam-style
namedtuple (epoch, nbatch, eval_metric, locals)."""
from __future__ import annotations

import logging
import time
from collections import namedtuple

__all__ = ["BatchEndParam", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "LogValidationMetricsCallback"]

BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving `net` parameters every `period` epochs
    (reference: callback.py:26 — saved symbol+params; here Gluon
    save_parameters)."""
    period = int(max(1, period))

    def _callback(epoch, net=None, **kwargs):  # noqa: ARG001
        if (epoch + 1) % period == 0 and net is not None:
            fname = f"{prefix}-{epoch + 1:04d}.params"
            net.save_parameters(fname)
            # checkpoint files are read by external consumers (upload
            # hooks, eval jobs) — barrier so the file exists when the
            # callback returns, like the reference's synchronous save
            # (save_parameters itself stays async; docs/migration.md)
            from .engine import waitall

            waitall()
            logging.info("Saved checkpoint to \"%s\"", fname)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the metric every `period` batches
    (reference: callback.py:64)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Logs samples/sec every `frequent` batches (reference:
    callback.py:91)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (
                    time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = " ".join(f"{n}={v:.6f}" for n, v in name_value)
                    logging.info("Epoch[%d] Batch [%d] Speed: %.2f "
                                 "samples/sec %s", param.epoch, count,
                                 speed, msg)
                else:
                    logging.info("Iter[%d] Batch [%d] Speed: %.2f "
                                 "samples/sec", param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """Text progress bar per epoch (reference: callback.py:155)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = int(round(100.0 * count / float(self.total)))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s", prog_bar, percents, "%")


class LogValidationMetricsCallback:
    """Epoch-end callback logging validation metrics (reference:
    callback.py:185)."""

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)
