"""npz round-trip codec for accelerator dtypes (bfloat16, float8*, int4).

numpy's .npz container writes ml_dtypes arrays as raw void records
(``|V2`` for bfloat16) and loads them back dtype-less, so a framework
whose native training dtype is bfloat16 could not checkpoint what it
trains (reference contract: dtype-preserving save/load,
include/mxnet/ndarray.h:425 — the legacy binary format stores
``type_flag_`` per blob).

TPU re-design: keep the portable .npz container, store each exotic
array as a bit-equal unsigned-int view, and record the true dtypes in
one reserved JSON key (:data:`DTYPE_KEY`). Files with no exotic arrays
are byte-identical to before, and remain loadable by plain numpy; old
checkpoints load unchanged (no sidecar key -> no decoding).
"""
from __future__ import annotations

import json

import numpy as _np

DTYPE_KEY = "__mx_npz_dtypes__"

# dtypes numpy cannot round-trip through .npy/.npz (registered by
# ml_dtypes; jax's bfloat16 IS ml_dtypes.bfloat16)
_EXOTIC = {}


def _exotic_map():
    if not _EXOTIC:
        import ml_dtypes

        for name in dir(ml_dtypes):
            if name.startswith(("float", "bfloat", "int", "uint")):
                try:
                    _EXOTIC[_np.dtype(getattr(ml_dtypes, name)).name] = (
                        _np.dtype(getattr(ml_dtypes, name)))
                except TypeError:
                    pass  # finfo/iinfo helpers
    return _EXOTIC


def _is_exotic(dt):
    dt = _np.dtype(dt)
    return dt.kind == "V" and dt.name in _exotic_map()


def _uint_view(dt):
    return _np.dtype({1: _np.uint8, 2: _np.uint16, 4: _np.uint32}[
        _np.dtype(dt).itemsize])


def encode_payload(arrays):
    """Return a dict safe for np.savez: exotic arrays become bit-equal
    uint views and their true dtypes land in the DTYPE_KEY sidecar.
    Returns the input dict unchanged (same object) when nothing is
    exotic, so the common f32 path costs one dtype check per array."""
    if DTYPE_KEY in arrays:
        raise ValueError(f"{DTYPE_KEY!r} is a reserved checkpoint key")
    sidecar = {}
    for k, a in arrays.items():
        if isinstance(a, _np.ndarray) and _is_exotic(a.dtype):
            sidecar[k] = a.dtype.name
    if not sidecar:
        return arrays
    out = {}
    for k, a in arrays.items():
        out[k] = a.view(_uint_view(a.dtype)) if k in sidecar else a
    out[DTYPE_KEY] = _np.frombuffer(
        json.dumps(sidecar).encode("utf-8"), dtype=_np.uint8)
    return out


def decode_entry(name, arr, sidecar):
    """Restore one array's true dtype given the parsed sidecar dict."""
    dt_name = sidecar.get(name)
    if dt_name is None:
        return arr
    return _np.asarray(arr).view(_exotic_map()[dt_name])


def read_sidecar(npz):
    """Parse the DTYPE_KEY entry of an open NpzFile (or dict). Returns
    {} for legacy/plain files."""
    files = getattr(npz, "files", None)
    keys = files if files is not None else npz.keys()
    if DTYPE_KEY not in keys:
        return {}
    return json.loads(bytes(npz[DTYPE_KEY]).decode("utf-8"))


def decode_npz(npz):
    """Materialize an open NpzFile (or dict) as {name: ndarray} with true
    dtypes restored and the sidecar key stripped."""
    sidecar = read_sidecar(npz)
    files = getattr(npz, "files", None)
    keys = files if files is not None else list(npz.keys())
    return {k: decode_entry(k, npz[k], sidecar)
            for k in keys if k != DTYPE_KEY}
