"""Typed framework errors (reference: python/mxnet/error.py). The
reference maps C++ error kinds onto python exception classes; the TPU
build raises python-native exceptions, so these classes exist for
except-clause parity in ported code."""
from .base import MXNetError

__all__ = ["MXNetError", "InternalError", "ValueError", "TypeError",
           "IndexError", "NotImplementedForSymbol", "register"]


class InternalError(MXNetError):
    """Framework-internal invariant violation."""


class NotImplementedForSymbol(MXNetError):
    def __init__(self, function, alias=None, *args):  # noqa: ARG002
        super().__init__(f"{getattr(function, '__name__', function)} is "
                         "not supported for Symbol")


ValueError = type("ValueError", (MXNetError, ValueError), {})  # noqa: A001
TypeError = type("TypeError", (MXNetError, TypeError), {})      # noqa: A001
IndexError = type("IndexError", (MXNetError, IndexError), {})   # noqa: A001

_ERR_REGISTRY = {}


def register(cls):
    """Register an error class by name (reference: error.py register)."""
    _ERR_REGISTRY[cls.__name__] = cls
    return cls
