"""Gluon Block / HybridBlock (reference: python/mxnet/gluon/block.py:202,1006).

Block: imperative container of Parameters and child Blocks; forward() runs
eagerly through the taped NDArray ops.

HybridBlock: hybridize() turns the block into the **jit boundary** — the
TPU-native CachedOp (reference: src/imperative/cached_op.cc). The first call
traces forward() into a jaxpr and compiles with jax.jit:

  * params enter the traced function as inputs (like CachedOp's data_indices),
  * a PRNG key input feeds dropout etc. via the trace key-provider
    (the FResourceRequest/kRandom analog),
  * stateful aux updates (BatchNorm running stats) are collected by a trace
    sink and returned as extra outputs, applied after each call — keeping the
    compiled function pure while preserving the reference's mutable-aux-input
    semantics,
  * autograd over the compiled op is ONE tape node via jax.vjp on the jitted
    function — the CachedOp::Backward analog, with XLA rematerialization
    available via mx.gluon.checkpoint (jax.checkpoint) instead of
    MXNET_BACKWARD_DO_MIRROR,
  * shape/dtype changes retrace automatically (SetForwardGraph parity);
    train/predict mode are separate compiled variants.
"""
from __future__ import annotations

import json
import re
import threading as _threading
import time

import jax
import jax.numpy as jnp
import numpy as _np

from .. import _random
from .. import autograd as ag
from ..diagnostics import introspect as _introspect
from ..diagnostics import spans as _spans
from ..passes import _state as _pass_state
from ..telemetry import instruments as _telemetry
from ..base import DeferredInitializationError, normalize_dtype
from ..device import Device, current_device
from ..ndarray.ndarray import NDArray
from .parameter import Constant, Parameter

__all__ = ["Block", "HybridBlock", "SymbolBlock", "current_state_sink"]


# ---------------------------------------------------------------------------
# trace-time state sink (BatchNorm running stats & friends)
# ---------------------------------------------------------------------------

class _StateSink:
    def __init__(self):
        self.params = []
        self.values = []

    def record(self, param, value_data):
        self.params.append(param)
        self.values.append(value_data)


_sink_stack = []


def current_state_sink():
    return _sink_stack[-1] if _sink_stack else None


class _push_sink:
    def __init__(self, sink):
        self._sink = sink

    def __enter__(self):
        _sink_stack.append(self._sink)
        return self._sink

    def __exit__(self, *exc):
        _sink_stack.pop()
        return False


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------

_hook_suppress = _threading.local()


def _hooks_suppressed():
    return getattr(_hook_suppress, "depth", 0) > 0


class _suppress_hooks:
    """Forward hooks stay silent during shape-inference dry passes (the
    deferred-init eager pass is plumbing, not a reportable forward)."""

    def __enter__(self):
        _hook_suppress.depth = getattr(_hook_suppress, "depth", 0) + 1

    def __exit__(self, *exc):
        _hook_suppress.depth -= 1


class HookHandle:
    """Detachable hook registration (reference: gluon/utils.py
    HookHandle — supports detach() and `with handle:`)."""

    def __init__(self, hooks_list, hook):
        self._hooks_list = hooks_list
        self._hook = hook

    def detach(self):
        if self._hook is not None and self._hook in self._hooks_list:
            self._hooks_list.remove(self._hook)
        self._hook = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.detach()
        return False


class Block:
    """Base container (reference: gluon/block.py:202)."""

    def __init__(self):
        object.__setattr__(self, "_children", {})
        object.__setattr__(self, "_reg_params", {})

    # -- attribute registration (reference: Block.__setattr__) -----------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            stale = self._children.get(name) is not value
            self._children[name] = value
            if stale:
                # structure changed: any compiled variant is stale
                # (reference: test_gluon.py test_hybrid_stale_cache)
                self._clear_cached()
        elif isinstance(value, Parameter):
            self._reg_params[name] = value
        else:
            existing = self._children.pop(name, None)
            if existing is None:
                self._reg_params.pop(name, None)
            elif existing is not value:
                self._clear_cached()
        object.__setattr__(self, name, value)

    def register_child(self, block, name=None):
        name = name or str(len(self._children))
        self._children[name] = block
        object.__setattr__(self, name, block)
        self._clear_cached()  # adding a child invalidates compiled variants
        return block

    def register_parameter(self, name, param):
        self._reg_params[name] = param
        object.__setattr__(self, name, param)
        return param

    # -- parameter collection ---------------------------------------------
    def _collect_params_with_prefix(self, prefix=""):
        out = {}
        for name, p in self._reg_params.items():
            out[prefix + name] = p
        for cname, child in self._children.items():
            out.update(child._collect_params_with_prefix(
                prefix + cname + "."))
        return out

    def collect_params(self, select=None):
        """Dict of structured-name -> Parameter (reference: collect_params).

        `select` is a regex over names ('.*weight', 'dense0_bias|...')."""
        params = self._collect_params_with_prefix()
        if select is None:
            return params
        pat = re.compile(select)
        return {k: v for k, v in params.items() if pat.match(k)}

    @property
    def params(self):
        return self._reg_params

    def initialize(self, init=None, device=None, verbose=False,
                   force_reinit=False, ctx=None):  # noqa: ARG002
        """Initialize all parameters (reference: Block.initialize)."""
        device = device if device is not None else ctx
        for name, p in self.collect_params().items():
            p._structured_name = name  # full path for Load/Mixed routing
            p.initialize(init=None, device=device,
                         default_init=init or _default_init(),
                         force_reinit=force_reinit)
        self._clear_cached()
        return self

    def _clear_cached(self):
        for child in self._children.values():
            child._clear_cached()

    def share_parameters(self, shared):
        """Replace this block's Parameters with the ones in `shared`
        (reference: Block.share_parameters, gluon/block.py — keys are
        structured names as produced by collect_params()). Unmatched
        names keep their own parameters; matched ones become the SAME
        Parameter object, so data and gradients are shared."""
        if shared is None:
            return self
        if not isinstance(shared, dict):
            raise ValueError(
                "share_parameters expects the dict collect_params() "
                f"returns, got {type(shared)}")

        def walk(block, prefix):
            for name in list(block._reg_params):
                full = prefix + name
                if full in shared:
                    block._reg_params[name] = shared[full]
                    object.__setattr__(block, name, shared[full])
            for cname, child in block._children.items():
                walk(child, prefix + cname + ".")

        walk(self, "")
        self._clear_cached()
        return self

    # -- forward ----------------------------------------------------------
    # -- hooks (reference: Block.register_forward_hook / _pre_hook,
    #    gluon/block.py + utils.HookHandle) --------------------------------
    def register_forward_hook(self, hook):
        """`hook(block, inputs, outputs)` after every forward; returns a
        detachable handle."""
        if not hasattr(self, "_fwd_hooks") or \
                not isinstance(self._fwd_hooks, list):
            object.__setattr__(self, "_fwd_hooks", list(
                getattr(self, "_fwd_hooks", ())))
        self._fwd_hooks.append(hook)
        return HookHandle(self._fwd_hooks, hook)

    def register_forward_pre_hook(self, hook):
        """`hook(block, inputs)` before every forward; returns a
        detachable handle."""
        if not hasattr(self, "_fwd_pre_hooks"):
            object.__setattr__(self, "_fwd_pre_hooks", [])
        self._fwd_pre_hooks.append(hook)
        return HookHandle(self._fwd_pre_hooks, hook)

    def __call__(self, *args, **kwargs):
        self._fire_fwd_pre_hooks(args)
        out = self.forward(*args, **kwargs)
        self._fire_fwd_hooks(args, out)
        return out

    def _fire_fwd_pre_hooks(self, args):
        pre = getattr(self, "_fwd_pre_hooks", ())
        if not pre or _hooks_suppressed():
            return
        # same tracer guard as _fire_fwd_hooks: hooks observe executed
        # values only — firing during a jit trace would crash value-
        # reading hooks and fire once per compile instead of per call
        for v in args:
            data = getattr(v, "_data", None)
            if data is not None and isinstance(data, jax.core.Tracer):
                return
        for hook in pre:
            hook(self, args)

    def _fire_fwd_hooks(self, args, out):
        hooks = getattr(self, "_fwd_hooks", ())
        if not hooks or _hooks_suppressed():
            return
        # never hand tracer-backed values to monitor callbacks: under jit
        # tracing a value-reading hook would crash (and fire only once at
        # trace time) — the reference's op hooks likewise observe only
        # executed values, not graph construction
        vals = list(args) + (list(out) if isinstance(out, (list, tuple))
                             else [out])
        for v in vals:
            data = getattr(v, "_data", None)
            if data is not None and isinstance(data, jax.core.Tracer):
                return
        for hook in hooks:
            hook(self, args, out)

    def forward(self, *args):
        raise NotImplementedError

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def cast(self, dtype):
        dtype = normalize_dtype(dtype)
        for p in self.collect_params().values():
            p.cast(dtype)
        self._clear_cached()
        return self

    def reset_ctx(self, ctx=None, device=None):
        for p in self.collect_params().values():
            p.reset_ctx(ctx=ctx, device=device)
        self._clear_cached()

    reset_device = reset_ctx

    def zero_grad(self):
        for p in self.collect_params().values():
            if p.grad_req != "null" and p._data_map is not None:
                p.zero_grad()

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def setattr(self, name, value):
        """Set an attribute on all parameters (reference: Block.setattr)."""
        for p in self.collect_params().values():
            setattr(p, name, value)

    # -- checkpoint --------------------------------------------------------
    def save_parameters(self, filename, deduplicate=False):  # noqa: ARG002
        """Save params as .npz keyed by structured names (reference:
        Block.save_parameters, gluon/block.py:340; format here is the
        cnpy/.npz path of src/serialization/cnpy.cc).

        ASYNC CONTRACT (deliberate divergence from the reference, which
        blocks on return): the write overlaps training on a native-engine
        IO thread. In-framework readers (load_parameters, nd.load) and
        mx.waitall() barrier correctly; an EXTERNAL consumer (shell cp, a
        second process, an upload hook) must call mx.waitall() first.
        `mx.nd.save` is synchronous-on-return like the reference if you
        need stat-after-save semantics. See docs/migration.md."""
        arrays = {}
        for name, p in self._collect_params_with_prefix().items():
            if p._data_map is None:
                continue
            # logical layout: files stay portable whether or not this
            # process re-laid the weight out (passes/layout.py)
            arrays[name] = _np.asarray(p.logical_data().asnumpy())
        # the serialize+write runs on a native-engine IO thread so training
        # continues while the checkpoint lands; loads (and waitall) barrier
        # on the path's engine var (_checkpoint_io; reference: engine-pushed
        # NDArray::Save)
        from .._checkpoint_io import async_save_npz

        async_save_npz(filename, arrays)

    def load_parameters(self, filename, device=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current", ctx=None):  # noqa: ARG002
        """Load params saved by save_parameters (reference: block.py:379)."""
        import os

        from .._checkpoint_io import wait_for_path

        wait_for_path(str(filename))  # barrier on any in-flight async save
        device = device if device is not None else ctx
        path = str(filename)
        if not os.path.exists(path) and os.path.exists(path + ".npz"):
            path = path + ".npz"
            wait_for_path(path)  # the save may have keyed the .npz name
        from .._dtype_codec import DTYPE_KEY, decode_entry, read_sidecar

        # restore bf16/f8 dtypes from the codec sidecar (npz alone loses
        # them to raw void records — a bf16-trained net must checkpoint).
        # Entries decode lazily: NpzFile decompresses per access, so a
        # partial load of a large checkpoint reads only what it needs.
        npz = _np.load(path, allow_pickle=False)
        sidecar = read_sidecar(npz)
        loaded = set(npz.files) - {DTYPE_KEY}
        params = self._collect_params_with_prefix()
        for name, p in params.items():
            if name not in loaded:
                if not allow_missing:
                    raise KeyError(
                        f"Parameter {name} missing in file {filename}; "
                        "set allow_missing=True to skip")
                continue
            arr = decode_entry(name, npz[name], sidecar)
            # dtype contract (reference: parameter.py:286-315 _load_init):
            # mismatch errors unless cast_dtype=True, which casts saved ->
            # current (dtype_source='current') or adopts the saved dtype
            # (dtype_source='saved')
            if cast_dtype and dtype_source not in ("current", "saved"):
                raise ValueError(
                    f"dtype_source must be 'current' or 'saved', got "
                    f"{dtype_source!r}")
            if p.dtype is not None and _np.dtype(p.dtype) != arr.dtype:
                if not cast_dtype:
                    raise AssertionError(
                        f"Failed loading Parameter '{name}' from saved "
                        f"params: dtype incompatible expected {p.dtype} vs "
                        f"saved {arr.dtype}. Set cast_dtype=True to cast "
                        "the dtype of saved params.")
                if dtype_source == "current":
                    arr = arr.astype(p.dtype, copy=False)
                else:  # 'saved': retype data AND grad buffers together
                    p.cast(arr.dtype)
            if p._data_map is None and p._deferred is None:
                p.shape = arr.shape
                p.initialize(device=device or current_device())
            elif p._deferred is not None:
                p._finish_deferred_init(arr.shape)
            p.set_data(NDArray(jnp.asarray(arr, p.dtype)))
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise KeyError(
                    f"file {filename} contains extra parameters {sorted(extra)}; "
                    "set ignore_extra=True to skip")
        self._clear_cached()

    # misc parity helpers
    def register_op_hook(self, callback, monitor_all=False):
        """Install a monitor callback on every descendant block's forward
        (reference: block.py:877 register_op_hook -> CachedOp::
        RegisterOpHook). callback(block_name, tensor_name, tensor) fires
        for each output (and each input when monitor_all=True).

        Granularity note: ops fuse inside the jit boundary on TPU, so the
        observable unit is the block forward — the analog of the
        reference hiding per-op detail under bulked exec
        (docs perf.md:293-296); hybridized blocks report at the jit
        boundary. Use MXNET_EXEC_BULK_EXEC-style de-optimization by
        calling .hybridize(active=False) for per-block detail."""
        def make_hook(prefix):
            def hook(block, inputs, output):
                name = prefix or type(block).__name__
                if monitor_all:
                    for i, a in enumerate(inputs):
                        callback(name, f"{name}_input{i}", a)
                outs = (output if isinstance(output, (list, tuple))
                        else [output])
                for i, o in enumerate(outs):
                    callback(name, f"{name}_output{i}", o)
            return hook

        def walk(block, prefix):
            block.register_forward_hook(make_hook(prefix))
            for cname, child in block._children.items():
                walk(child, f"{prefix}.{cname}" if prefix else cname)

        walk(self, "")
        return self

    def summary(self, *inputs):
        """Print a per-layer summary (reference: Block.summary)."""
        rows = []

        def walk(block, prefix):
            n_params = sum(
                int(_np.prod(p.shape)) for p in block._reg_params.values()
                if p.shape is not None)
            rows.append((prefix or type(block).__name__,
                         type(block).__name__, n_params))
            for name, child in block._children.items():
                walk(child, f"{prefix}.{name}" if prefix else name)

        walk(self, "")
        total = sum(r[2] for r in rows)
        print(f"{'Layer':<40}{'Type':<24}{'Params':>12}")
        print("-" * 76)
        for name, typ, n in rows:
            print(f"{name:<40}{typ:<24}{n:>12}")
        print("-" * 76)
        print(f"Total params: {total}")
        return total


def _default_init():
    from .. import initializer

    return initializer.Uniform()


def _traced_forward(block, params, training, param_data, key, input_datas):
    """Shared trace body for the CachedOp jit and as_pure_function: run
    block.forward with traced param stand-ins, a folded-key RNG provider,
    and a state sink collecting aux writes. Returns (out_datas, sink)."""
    sink = _StateSink()
    counter = [0]

    def key_provider():
        counter[0] += 1
        return jax.random.fold_in(key, counter[0])

    wrapped = [NDArray(d) for d in input_datas]
    with ag.suspend_taping(), ag._scope(training=training), \
            _push_sink(sink), _random.key_provider(key_provider):
        for name, p in params:
            p._traced_data = NDArray(param_data[name])
        try:
            out = block.forward(*wrapped)
        finally:
            for _, p in params:
                p._traced_data = None
    out_datas = jax.tree_util.tree_map(
        lambda a: a._data if isinstance(a, NDArray) else a, out,
        is_leaf=lambda a: isinstance(a, NDArray))
    return out_datas, sink


# ---------------------------------------------------------------------------
# HybridBlock
# ---------------------------------------------------------------------------

# The ops allowed to trip the dynamic-graph fallback: every op whose
# OUTPUT shape is value-dependent snapshots its inputs eagerly on the
# host (contrib/ops.py), which raises a concretization error under jit
# tracing by design. A concretization error from anywhere else is a user
# tracing bug and must propagate (ADVICE.md block.py:581).
_DYNAMIC_OUTPUT_OPS = frozenset({
    "boolean_mask", "box_nms", "bipartite_matching", "multibox_target",
    "multibox_detection", "dynamic_reshape", "getnnz", "proposal",
})


def _dynamic_output_origin(exc):
    """Name of the known dynamic-output op the concretization error was
    raised under, walking its traceback; None when the error came from
    user control flow (or any frame outside the framework's op table)."""
    import os

    tb = exc.__traceback__
    while tb is not None:
        code = tb.tb_frame.f_code
        if code.co_name in _DYNAMIC_OUTPUT_OPS and \
                os.sep + "mxnet_tpu" + os.sep in code.co_filename:
            return code.co_name
        tb = tb.tb_next
    return None


class HybridBlock(Block):
    """Block that can compile its forward as one XLA program."""

    def __init__(self):
        super().__init__()
        object.__setattr__(self, "_active", False)
        object.__setattr__(self, "_jit_variants", {})
        object.__setattr__(self, "_cached_param_list", None)
        object.__setattr__(self, "_state_params", {})
        object.__setattr__(self, "_flags", {})
        # per-variant retrace counter: cached_fn bumps it once per jit
        # trace (= one XLA compile, including shape-signature misses
        # AFTER the variant was first built — which _jit_variants alone
        # can't see). serving.InferenceEngine.warmup() reads it to prove
        # every bucket is pre-compiled.
        object.__setattr__(self, "_trace_counts", {})
        # thread-safe CachedOp analog (reference:
        # src/imperative/cached_op_threadsafe.cc): one lock guards variant
        # build + aux-state swap so concurrent inference threads share the
        # compiled executable safely. Executing the jitted fn itself is
        # thread-safe (XLA executables are immutable).
        import threading as _threading

        object.__setattr__(self, "_cache_lock", _threading.RLock())

    def hybridize(self, active=True, backend=None, backend_opts=None,
                  **kwargs):  # noqa: ARG002
        """Enable compiled execution (reference: HybridBlock.hybridize;
        static_alloc/static_shape flags are accepted — XLA always runs
        static-shape, buffer reuse is PJRT's job)."""
        object.__setattr__(self, "_active", active)
        self._flags.update(kwargs)
        self._jit_variants.clear()
        # children stay eager; this block is the jit boundary — but mark
        # nested HybridBlocks inactive to avoid double tracing.
        for child in self._children.values():
            child.hybridize(False)

    def optimize_for(self, x, *args, backend=None, backend_opts=None,
                     **kwargs):  # noqa: ARG002
        """Compile with an optional subgraph backend (reference:
        HybridBlock.optimize_for, block.py:1281 → build_subgraph.cc).

        With backend=None this is hybridize+run. With a registered
        backend name (mxnet_tpu.subgraph.register_backend), the traced
        jaxpr is partitioned: maximal regions matched by the backend are
        replaced by its substituted implementations, and the partitioned
        program becomes this block's compiled variant."""
        self.hybridize(True)
        if backend is None:
            return self(x, *args)
        # record the backend; the variant is (re)built from it on demand —
        # so cast()/load_parameters()/_clear_cached() cannot silently drop
        # the partitioned program (reference: HybridBlock remembers its
        # backend and re-partitions in _build_cache)
        object.__setattr__(self, "_variant_builder", ("subgraph", backend))
        object.__setattr__(self, "_subgraph_backend", backend)
        self._jit_variants.clear()
        return self(x, *args)

    def _clear_cached(self):
        jv = getattr(self, "_jit_variants", None)
        if jv is not None:  # may fire from __setattr__ mid-__init__
            jv.clear()
        super()._clear_cached()

    def __call__(self, *args, **kwargs):
        self._fire_fwd_pre_hooks(args)
        concrete_tensors = (
            not kwargs and bool(args)
            and all(isinstance(a, NDArray) for a in args)
            and not any(isinstance(a._data, jax.core.Tracer) for a in args))
        if concrete_tensors:
            # remember input signature for export() (reference: CachedOp
            # remembers bound shapes via SetForwardGraph)
            object.__setattr__(
                self, "_last_input_specs",
                [(tuple(a.shape), a.dtype) for a in args])
            if self._active and not getattr(self, "_dynamic_graph", False):
                try:
                    return self._call_cached(*args)
                except (jax.errors.TracerArrayConversionError,
                        jax.errors.ConcretizationTypeError) as e:
                    # Concretization during trace has two causes with
                    # opposite remedies. (1) A known dynamic-OUTPUT op
                    # (boolean_mask, box_nms selection — value-dependent
                    # shapes XLA cannot trace): the reference CachedOp
                    # flips to dynamic-shape execution (imperative
                    # per-op) for such graphs, and we do the same — run
                    # this block eagerly from now on, hybridize() a
                    # no-op for it. (2) A genuine tracing bug in user
                    # control flow (`if x > 0:` on a traced value):
                    # falling back would permanently mask the bug AND
                    # silently lose compiled performance (ADVICE.md
                    # block.py:581), so anything NOT raised from inside
                    # a known dynamic-output op re-raises.
                    op = _dynamic_output_origin(e)
                    if op is None:
                        raise
                    import warnings

                    _telemetry.record_fallback(type(self).__name__)
                    warnings.warn(
                        f"{type(self).__name__}.forward contains the "
                        f"dynamic-output op '{op}'; running imperatively "
                        "(reference CachedOp dynamic-shape mode). "
                        f"Original error: {type(e).__name__}: {e}",
                        stacklevel=2)
                    object.__setattr__(self, "_dynamic_graph", True)
        out = self.forward(*args, **kwargs)
        self._fire_fwd_hooks(args, out)
        return out

    # -- deferred shape inference -----------------------------------------
    def infer_shape(self, *args):
        """Run a shape-only eager pass so deferred params materialize
        (reference: HybridBlock.infer_shape, block.py:1462)."""
        with ag.pause(), _suppress_hooks():
            self.forward(*args)

    # -- the CachedOp ------------------------------------------------------
    def _ensure_initialized(self, args):
        try:
            for p in self.collect_params().values():
                if p.grad_req or True:
                    p._check_initialized()
            return
        except DeferredInitializationError:
            # one eager pass completes deferred init (layers infer
            # shapes); monitor hooks stay silent — it is plumbing
            with ag.pause(), _suppress_hooks():
                self.forward(*args)

    def _make_cached_fn(self, training):
        """The traceable whole-block function (shared by the plain jit
        variant and the subgraph-partitioned variant)."""
        params = sorted(self.collect_params().items())
        object.__setattr__(self, "_cached_param_list", params)
        block = self

        def cached_fn(param_data, key, *input_datas):
            # host side effect: this body runs once per jit trace (new
            # shape/dtype signature -> one XLA compile), never on cache
            # hits — the retrace signal jit_trace_count() exposes.
            # Suppressed while the pass pipeline (or compile
            # introspection) re-traces for its own purposes: the
            # pipeline fires ctx.on_build once per built entry instead.
            if not _pass_state.suppressed():
                block._bump_trace(training)
            out_datas, sink = _traced_forward(
                block, params, training, param_data, key, input_datas)
            # trace-time side effect: remember which params get aux updates
            # (per train/predict variant — predict traces have no BN updates)
            block._state_params[training] = list(sink.params)
            return out_datas, tuple(sink.values)

        return cached_fn

    def _bump_trace(self, training):
        with self._cache_lock:
            self._trace_counts[training] = \
                self._trace_counts.get(training, 0) + 1
        _telemetry.record_trace(
            type(self).__name__, "train" if training else "predict")

    def jit_trace_count(self, training=False):
        """How many times the train/predict variant has been traced —
        each trace is one XLA compile (first build plus every
        shape/dtype-signature cache miss since). Monotonic across
        hybridize()/_clear_cached(); the serving warmup's zero-miss
        proof snapshots it before and after driving every bucket."""
        return self._trace_counts.get(bool(training), 0)

    def call_cached_graph(self, *args):
        """Thread-safe entry into the compiled predict-mode graph — the
        serving hot path (serving/engine.py, docs/serving.md).

        Forces predict mode and no taping regardless of the calling
        thread's autograd state, and never falls back to eager: a block
        that already dropped to dynamic-graph execution (or was never
        hybridized) cannot honor the bucketed-compile-cache contract, so
        this raises instead of silently serving uncompiled. Safe to call
        from many threads at once — variant build is serialized by the
        cache lock, and executing the jitted function is reentrant (XLA
        executables are immutable)."""
        if not self._active:
            raise RuntimeError(
                f"{type(self).__name__}.call_cached_graph requires "
                "hybridize() — the serving engine only runs compiled "
                "graphs")
        if getattr(self, "_dynamic_graph", False):
            raise RuntimeError(
                f"{type(self).__name__} fell back to dynamic-graph "
                "(imperative) execution; it cannot be served through "
                "the bucketed jit cache")
        with ag.pause():
            return self._call_cached(*args)

    def aot_introspect(self, variant, *args, label=None):
        """AOT-lower the predict-mode graph at ``args``' exact signature
        and record XLA's cost/memory analysis in the diagnostics compile
        registry under ``(label or class name, variant)``.

        serving.InferenceEngine.warmup() calls this once per batch
        bucket, so the registry proves which shapes are pre-compiled
        (and what each costs) — the per-bucket analog of the cache-miss
        capture in _call_cached. Costs one extra XLA compile per call;
        gated by MXTPU_DIAG_COMPILE like every introspection. Returns
        the registry entry dict or None."""
        with ag.pause():
            if self._jit_variants.get(False) is None:
                self._call_cached(*args)  # builds the predict variant
            jitted = self._jit_variants.get(False)
            if jitted is None:
                return None
            pd = {n: p.data()._data for n, p in self._cached_param_list}
            key = _random.next_key()
            datas = [a._data for a in args]
            return _introspect.capture_compile(
                label or type(self).__name__, variant, jitted,
                (pd, key, *datas))

    def pass_pipeline(self):
        """This block's graph-pass pipeline (docs/passes.md): a
        passes.PassManager whose registered passes rewrite every
        compiled variant — block jit, export, symbol lowering.  Call
        ``hybridize(True)`` (or clear the jit cache) after changing the
        pipeline so already-built variants rebuild through it."""
        from .. import passes as _passes

        pm = getattr(self, "_pass_manager", None)
        if pm is None:
            pm = _passes.PassManager()
            object.__setattr__(self, "_pass_manager", pm)
        return pm

    def _build_jit(self, training):
        from .. import passes as _passes

        return _passes.apply(self._make_cached_fn(training),
                             _passes.block_context(self, training))

    def _build_variant(self, training, args):
        """Build the compiled variant honoring any recorded graph rewrite
        (subgraph backend / AMP graph pass)."""
        builder = getattr(self, "_variant_builder", None)
        if builder is None:
            return self._build_jit(training)
        kind, payload = builder
        cached_fn = self._make_cached_fn(training)
        pd = {n: p.data()._data for n, p in self._cached_param_list}
        key = _random.next_key()
        datas = [a._data for a in args]
        if kind == "subgraph":
            from .. import passes as _passes
            from .. import subgraph as _subgraph

            part, n_sub = _subgraph.partition_call(
                cached_fn, payload, pd, key, *datas)
            object.__setattr__(self, "_subgraph_count", n_sub)
            # bump=False: partition_call already traced cached_fn once
            # (bump fired there); the partitioned wrapper itself never
            # self-bumped under a plain jit either
            return _passes.apply(
                part, _passes.block_context(self, training, bump=False))
        if kind == "amp_graph":
            from ..amp.graph_pass import build_amp_variant

            fn, stats = build_amp_variant(cached_fn, payload, pd, key,
                                          datas)
            object.__setattr__(self, "_amp_stats", stats)
            return fn
        raise ValueError(f"unknown variant builder {kind!r}")

    def _call_cached(self, *args):
        training = bool(ag.is_training())
        compile_t0 = None  # set on cache miss: this call traces + compiles
        jitted = self._jit_variants.get(training)
        if jitted is None:
            # one thread completes deferred init + builds; others reuse
            # (reference: cached_op_threadsafe.cc serializes graph setup)
            with self._cache_lock:
                jitted = self._jit_variants.get(training)
                if jitted is None:
                    self._ensure_initialized(args)
                    # persistent NHWC weight re-layout BEFORE the first
                    # trace: the captured program sees HWIO invars, so
                    # layout costs no extra compile (passes/layout.py;
                    # MXTPU_LAYOUT=off returns immediately)
                    from ..passes import layout as _layout_pass

                    _layout_pass.prepare_block(self)
                    compile_t0 = time.perf_counter()
                    with _spans.span(type(self).__name__, cat="compile"):
                        jitted = self._build_variant(training, args)
                    self._jit_variants[training] = jitted
        else:
            self._ensure_initialized(args)
            if not getattr(self, "_layout_prepared", False):
                from ..passes import layout as _layout_pass

                _layout_pass.prepare_block(self)
        params = self._cached_param_list
        names = [n for n, _ in params]
        param_nds = [p.data() for _, p in params]
        pd = {n: nd._data for n, nd in zip(names, param_nds)}
        key = _random.next_key()
        if params:
            # mesh-placed params (sharding.ShardingPlan.apply) commit the
            # computation to the mesh's device set; the key is committed to
            # the default device, and jit refuses mixed assignments —
            # replicate it onto the same mesh.
            _shd = getattr(pd[names[0]], "sharding", None)
            _mesh = getattr(_shd, "mesh", None)
            if _mesh is not None and len(_shd.device_set) > 1:
                key = jax.device_put(
                    key,
                    jax.sharding.NamedSharding(
                        _mesh, jax.sharding.PartitionSpec()))
        arr_datas = [a._data for a in args]

        taping = ag.taping_active() and (
            any(p.grad_req != "null" for _, p in params)
            or any(a._requires_grad_entry for a in args)
        )

        with _spans.span(type(self).__name__, cat="fwd"):
            if taping:
                def fn(pd_, *xs):
                    out, state = jitted(pd_, key, *xs)
                    return out, state

                out_datas, vjp_fn, state_vals = jax.vjp(
                    fn, pd, *arr_datas, has_aux=True)
            else:
                out_datas, state_vals = jitted(pd, key, *arr_datas)

        if compile_t0 is not None:
            # the whole cache-miss call is the compile cost users feel:
            # trace + XLA compile + first dispatch (async — the device run
            # itself isn't awaited here)
            variant = "train" if training else "predict"
            compile_seconds = time.perf_counter() - compile_t0
            _telemetry.record_compile(
                type(self).__name__, variant, compile_seconds)
            # AOT-introspect what XLA built for this signature: flops,
            # bytes accessed, arg/out/temp sizes → the compile registry
            # (diagnostics.report / tools/diagnose.py). Costs one extra
            # compile per variant; MXTPU_DIAG_COMPILE=0 skips.
            _introspect.capture_compile(
                type(self).__name__, variant, jitted,
                (pd, key, *arr_datas), compile_seconds=compile_seconds)

        # apply aux state updates (BN running stats) — serialized so
        # concurrent threads cannot interleave half-written stats
        state_params = self._state_params.get(training) or ()
        if state_params:
            with self._cache_lock:
                for p, v in zip(state_params, state_vals):
                    target = p.data() if isinstance(p, Parameter) else p
                    target._data = v
                    target._version += 1

        flat_out, treedef = jax.tree_util.tree_flatten(out_datas)
        wrapped_flat = [NDArray(o) for o in flat_out]

        if taping:
            nd_inputs = param_nds + list(args)

            def node_vjp(out_ct):
                cts = out_ct if isinstance(out_ct, tuple) else (out_ct,)
                ct_tree = jax.tree_util.tree_unflatten(treedef, list(cts))
                all_cts = vjp_fn(ct_tree)
                pd_ct = all_cts[0]
                x_cts = all_cts[1:]
                flat_pd = [pd_ct[n] for n in names]
                return tuple(flat_pd) + tuple(x_cts)

            node = ag.TapeNode(
                node_vjp,
                nd_inputs,
                [a._tape_entry for a in nd_inputs],
                [(tuple(o.shape), o.dtype) for o in flat_out],
                multi_out=len(flat_out) > 1,
                name=f"CachedOp({type(self).__name__})",
            )
            for idx, w in enumerate(wrapped_flat):
                w._tape_entry = (node, idx)

        out = jax.tree_util.tree_unflatten(treedef, wrapped_flat)
        for hook in getattr(self, "_fwd_hooks", ()):
            hook(self, args, out)
        return out

    # -- pure functional view ---------------------------------------------
    def as_pure_function(self, training=False):
        """Return (fn, params) where fn(params, key, *inputs) ->
        (out, new_params) is a PURE jax function of the whole block.

        This is the TPU-native export of the CachedOp: the function is
        jit/pjit/shard_map-able, differentiable, and shardable; aux-state
        updates (BN running stats) come back in new_params instead of
        mutating. Used by bench.py, __graft_entry__ and the sharded
        training paths.
        """
        params = sorted(self.collect_params().items())
        block = self

        def fn(param_data, key, *input_datas):
            out_datas, sink = _traced_forward(
                block, params, training, param_data, key, input_datas)
            name_of = {id(p): n for n, p in params}
            new_params = dict(param_data)
            for p, v in zip(sink.params, sink.values):
                new_params[name_of[id(p)]] = v
            return out_datas, new_params

        param_data = {n: p.data()._data for n, p in params}
        return fn, param_data

    def trainable_param_names(self):
        """Names of params with grad_req != 'null' (BN stats excluded)."""
        return [n for n, p in sorted(self.collect_params().items())
                if p.grad_req != "null"]

    # -- export ------------------------------------------------------------
    def export(self, path, epoch=0, remove_amp_cast=True):  # noqa: ARG002
        """Export for deployment (reference: HybridBlock.export →
        model-symbol.json + model-0000.params, block.py:1480).

        TPU-native artifact: params .npz + the inference program serialized
        as portable StableHLO via jax.export — the AOT-compiled-graph role
        model-symbol.json played. Round-trips through SymbolBlock.imports.
        Requires one prior call (to know input shapes)."""
        specs = getattr(self, "_last_input_specs", None)
        if specs is None:
            raise RuntimeError(
                "export needs input shapes: call the block once first")
        params_file = f"{path}-{epoch:04d}.params.npz"
        self.save_parameters(params_file)
        fn, param_data = self.as_pure_function(training=False)
        key = jax.random.PRNGKey(0)

        def infer_fn(pd, *xs):
            out, _ = fn(pd, key, *xs)
            return out

        from jax import export as jax_export

        from .. import passes as _passes

        # through the pipeline: a converted/remat'd block exports the
        # SAME program it runs (apply returns a real jax.jit, which
        # jax_export requires)
        jitted = _passes.apply(infer_fn, _passes.PassContext(
            block=self, label=type(self).__name__, variant="export",
            kind="export"))
        exp = jax_export.export(jitted)(
            {n: jax.ShapeDtypeStruct(a.shape, a.dtype)
             for n, a in param_data.items()},
            *[jax.ShapeDtypeStruct(s, d) for s, d in specs])
        hlo_file = f"{path}-{epoch:04d}.stablehlo.bin"
        with open(hlo_file, "wb") as f:
            f.write(exp.serialize())
        meta = {
            "format": "mxnet_tpu-stablehlo",
            "class": type(self).__name__,
            "params": params_file,
            "stablehlo": hlo_file,
            "inputs": [[list(s), str(_np.dtype(d))] for s, d in specs],
        }
        with open(f"{path}-symbol.json", "w") as f:
            json.dump(meta, f, indent=2)
        return f"{path}-symbol.json", params_file


class SymbolBlock(HybridBlock):
    """Run a graph artifact as a Block (reference: gluon/block.py:1654).

    Two artifact kinds:
      * an mx.symbol DAG (``SymbolBlock(outputs, inputs, params=...)`` or a
        saved symbol json) — evaluated through the symbol op table;
      * a StableHLO bundle from HybridBlock.export — rehydrated with
        jax.export.deserialize (inference only, like a deployed
        model-symbol.json was).
    """

    def __init__(self, outputs=None, inputs=None, params=None):
        super().__init__()
        object.__setattr__(self, "_exported", None)
        object.__setattr__(self, "_symbol", None)
        object.__setattr__(self, "_input_names", [])
        object.__setattr__(self, "_arg_params", {})
        if outputs is None:
            return  # imports() fills in
        from ..symbol.symbol import Symbol as Sym

        if isinstance(outputs, (list, tuple)):
            from ..symbol.symbol import Group

            outputs = Group(list(outputs))
        if not isinstance(outputs, Sym):
            raise TypeError("outputs must be a Symbol")
        if inputs is None:
            raise ValueError("SymbolBlock needs the input symbols")
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        object.__setattr__(self, "_symbol", outputs)
        object.__setattr__(
            self, "_input_names", [s.name for s in inputs])
        arg_names = [n for n in outputs.list_arguments()
                     if n not in self._input_names]
        params = params or {}
        for n in arg_names:
            p = Parameter(name=n, shape=None)
            if n in params:
                v = params[n]
                arr = v.data() if isinstance(v, Parameter) else v
                if isinstance(arr, NDArray):
                    arr = arr._data
                p.shape = tuple(arr.shape)
                p.initialize(device=current_device())
                p.set_data(NDArray(jnp.asarray(arr)))
            self._arg_params[n] = p
            self.register_parameter(n.replace(".", "_"), p)

    @staticmethod
    def imports(symbol_file, input_names=("data",), param_file=None,
                ctx=None, device=None, allow_missing=False):  # noqa: ARG004
        """Load an exported artifact (reference: SymbolBlock.imports)."""
        import os

        with open(symbol_file) as f:
            head = f.read()
        blk = SymbolBlock()
        if isinstance(input_names, str):
            input_names = [input_names]
        try:
            meta = json.loads(head)
        except json.JSONDecodeError:
            meta = None
        if meta and meta.get("format") == "mxnet_tpu-stablehlo":
            from jax import export as jax_export

            base = os.path.dirname(os.path.abspath(symbol_file))
            from .._checkpoint_io import wait_for_path

            def _resolve(p):
                # barrier BEFORE the existence probe — an in-flight async
                # save would otherwise redirect to the wrong path. Try the
                # path as given, its basename next to the symbol file, and
                # each one's .npz twin (a reference-era caller passes
                # "net-0000.params"; export writes "net-0000.params.npz").
                cands = [p, os.path.join(base, os.path.basename(p))]
                cands += [c + ".npz" for c in cands]
                for c in cands:
                    wait_for_path(c)
                    if os.path.exists(c):
                        return c
                return cands[0]

            with open(_resolve(meta["stablehlo"]), "rb") as f:
                exported = jax_export.deserialize(f.read())
            from .._dtype_codec import decode_npz

            loaded = decode_npz(_np.load(
                _resolve(param_file or meta["params"]),
                allow_pickle=False))
            object.__setattr__(blk, "_exported", exported)
            object.__setattr__(
                blk, "_arg_params",
                {n: jnp.asarray(a) for n, a in loaded.items()})
            object.__setattr__(blk, "_input_names", list(input_names))
            return blk
        if meta and meta.get("format") == "mxnet_tpu-symbol":
            from ..symbol.symbol import fromjson

            sym = fromjson(head)
            from ..symbol.symbol import var as sym_var

            inputs = [sym_var(n) for n in input_names]
            blk2 = SymbolBlock(sym, inputs)
            if param_file:
                blk2.load_parameters(param_file,
                                     allow_missing=allow_missing)
            return blk2
        raise ValueError(f"unrecognized artifact {symbol_file}")

    def forward(self, *args):
        if self._exported is not None:
            datas = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                     for a in args]
            out = self._exported.call(self._arg_params, *datas)
            out = jax.tree_util.tree_map(NDArray, out)
            if isinstance(out, (list, tuple)) and len(out) == 1:
                return out[0]
            return out
        if self._symbol is None:
            raise RuntimeError("empty SymbolBlock")
        # lower + jit once (Executor does the same); retraces only on
        # shape/dtype change via jit's cache
        jitted = getattr(self, "_sym_jit", None)
        if jitted is None:
            from .. import passes as _passes

            jitted = _passes.apply(
                self._symbol._lower(),
                _passes.PassContext(block=self,
                                    label=type(self).__name__,
                                    variant="symbol", kind="symbol"))
            object.__setattr__(self, "_sym_jit", jitted)
        feed = {}
        for n, a in zip(self._input_names, args):
            feed[n] = a._data if isinstance(a, NDArray) else jnp.asarray(a)
        for n, p in self._arg_params.items():
            feed[n] = p.data()._data
        outs = jitted(feed)
        outs = [NDArray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs
