"""Gluon Parameter (reference: python/mxnet/gluon/parameter.py:47).

Holds weight data (+ gradient buffer) with deferred initialization: a
Parameter created with unknown dims (0/-1/None) materializes on the first
forward once the layer infers the full shape. Supports per-device copies for
multi-device data-parallel training (the reference's `ctx` list), grad_req
write/add/null, lr_mult/wd_mult, and trace mode (during CachedOp tracing the
parameter temporarily exposes a jax tracer instead of its concrete buffer).
"""
from __future__ import annotations

import uuid

import threading as _threading

import jax.numpy as jnp
import numpy as _np

from .. import initializer as init_mod
from ..base import DeferredInitializationError, normalize_dtype
from ..device import Device, current_device
from ..ndarray.ndarray import NDArray, _wrap_out

__all__ = ["Parameter", "Constant"]


def _shape_known(shape):
    return shape is not None and all(
        d is not None and int(d) > 0 for d in shape
    )


class Parameter:
    def __init__(self, name="weight", grad_req="write", shape=None,
                 dtype=_np.float32, lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):  # noqa: ARG002
        if grad_stype not in ("default", "row_sparse"):
            raise ValueError(f"grad_stype must be 'default' or "
                             f"'row_sparse', got {grad_stype!r}")
        # row_sparse grads: the tape still accumulates densely (XLA
        # scatter-add is the efficient TPU path), but the Trainer hands the
        # optimizer a RowSparseNDArray sliced to the rows the forward
        # touched (see _as_row_sparse_grad), so lazy_update semantics match
        # the reference (optimizer/sgd.py:36-95) without a host sync.
        self.grad_stype = grad_stype
        self._sparse_row_hints = []   # index arrays recorded by Embedding
        self._name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = normalize_dtype(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = bool(differentiable)
        # validate FIRST (the setter), then the setter's own coercion
        # downgrades non-differentiable params to 'null'. The ctor default
        # grad_req='write' on a differentiable=False parameter coerces
        # SILENTLY (Constant, BN running stats — nothing the caller chose);
        # the setter warns only on an explicit non-default request or a
        # post-construction reassignment.
        if not self._differentiable and grad_req == "write":
            grad_req = "null"
        self.grad_req = grad_req
        self._data_map = None  # {Device: NDArray}
        self._grad_map = None
        self._ctx_list = None
        self._deferred = None  # (init, device_list, default_init)
        # persistent physical layout (passes/layout.py prepare_block):
        # None = physical == logical; else data()/grad() buffers hold
        # transpose(logical, _layout_perm) while self.shape, set_data,
        # logical_data and every checkpoint stay in the LOGICAL layout
        self._layout_perm = None
        # tracer visible during CachedOp tracing — THREAD-LOCAL so a trace
        # in one thread cannot leak tracers into concurrent inference
        # threads (reference: cached_op_threadsafe.cc isolation)
        self._tls = _threading.local()

    @property
    def _traced_data(self):
        return getattr(self._tls, "traced_data", None)

    @_traced_data.setter
    def _traced_data(self, value):
        self._tls.traced_data = value

    # -- identity ----------------------------------------------------------
    @property
    def name(self):
        return self._name

    @name.setter
    def name(self, value):
        self._name = value

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        # fill unknown dims; known dims must match (reference shape merge)
        merged = []
        for old, new in zip(self._shape, new_shape):
            if old in (0, -1, None):
                merged.append(new)
            else:
                if new not in (0, -1, None) and int(old) != int(new):
                    raise ValueError(
                        f"Parameter {self._name}: shape mismatch "
                        f"{self._shape} vs {tuple(new_shape)}")
                merged.append(old)
        self._shape = tuple(merged)

    def __repr__(self):
        return (f"Parameter {self._name} (shape={self._shape}, "
                f"dtype={self.dtype})")

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, device=None, default_init=None,
                   force_reinit=False, ctx=None):
        if device is None:
            device = ctx
        if device is None:
            device = current_device()
        devices = device if isinstance(device, (list, tuple)) else [device]
        devices = [d if isinstance(d, Device) else Device(d) for d in devices]
        if self._data_map is not None and not force_reinit:
            return
        default_init = default_init or init_mod.Uniform()
        if not _shape_known(self._shape):
            if not self.allow_deferred_init:
                raise ValueError(
                    f"Cannot initialize Parameter {self._name}: unknown "
                    f"shape {self._shape} and allow_deferred_init=False")
            self._deferred = (init, devices, default_init)
            return
        self._finish_init(init, devices, default_init)

    def _finish_init(self, init, devices, default_init):
        # create() resolves registry-name strings and passes Initializer
        # instances through, so one call covers every spec form
        # (net.initialize(init="normal") included)
        # Reference protocol (gluon/parameter.py:365): the GLOBAL
        # initializer's __call__ drives, with the parameter's declared
        # init riding in InitDesc.attrs['__init__']. Standard globals
        # defer to the declared init (biases stay zero because layers
        # declare 'zeros'); Load/Mixed override __call__ and so win —
        # net.initialize(init=Load(...)) warm-starts EVERY parameter.
        declared = init if init is not None else self.init
        global_init = init_mod.create(default_init)
        init_name = getattr(self, "_structured_name", None) or self._name
        desc = init_mod.InitDesc(
            init_name,
            {"__init__": declared} if declared is not None else {})
        master = global_init.init_array(desc, self._shape, self.dtype,
                                        explicit=declared is None)
        self._ctx_list = list(devices)
        self._data_map = {d: master.copyto(d) for d in devices}
        self._grad_map = {}
        if self.grad_req != "null":
            self._init_grad_buffers()
        self._deferred = None

    def _init_grad_buffers(self):
        """(Re)allocate fresh zero grad buffers on every device and wire
        them to the data arrays — the ONE copy of this logic
        (reference parameter.py _init_grad). Fresh zeros on every
        grad_req change: reused buffers would feed stale gradients into
        an 'add' accumulation."""
        self._grad_map = {}
        shape = self._shape if self._layout_perm is None \
            else tuple(self._shape[i] for i in self._layout_perm)
        for d, arr in self._data_map.items():
            g = _wrap_out(jnp.zeros(shape, self.dtype)).copyto(d)
            self._grad_map[d] = g
            arr._grad = g
            arr._grad_req = self._grad_req

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        """Validated + live: changing grad_req after initialize rewires
        the per-device arrays (reference parameter.py grad_req setter —
        'add' starts accumulating into FRESH zeros, 'null' drops the
        buffers, non-differentiable parameters coerce to 'null')."""
        if req not in ("write", "add", "null"):
            raise ValueError(
                f"grad_req must be 'write', 'add' or 'null', got {req!r}")
        if not getattr(self, "_differentiable", True) and req != "null":
            import warnings

            warnings.warn(
                f"parameter {getattr(self, '_name', '?')!r} is not "
                f"differentiable; ignoring grad_req={req!r}",
                stacklevel=2)
            req = "null"
        if req == getattr(self, "_grad_req", None):
            # same-value reassignment keeps accumulated gradients
            # (reference setter early-returns; Block.setattr loops every
            # parameter unconditionally)
            return
        self._grad_req = req
        data_map = getattr(self, "_data_map", None)
        if not data_map:
            return
        if req == "null":
            for arr in data_map.values():
                arr._grad = None
                arr._grad_req = req
            self._grad_map = {}
            return
        self._init_grad_buffers()

    def _finish_deferred_init(self, shape=None):
        """Complete deferred init once the full shape is known."""
        if shape is not None:
            self.shape = shape
        if self._deferred is None:
            raise DeferredInitializationError(
                f"Parameter {self._name} was not initialized "
                f"(call .initialize() first)")
        if not _shape_known(self._shape):
            raise DeferredInitializationError(
                f"Parameter {self._name}: shape still unknown {self._shape}")
        init, devices, default_init = self._deferred
        self._finish_init(init, devices, default_init)

    @property
    def _is_deferred(self):
        return self._data_map is None and self._deferred is not None

    def _check_initialized(self, device=None):
        if self._data_map is None:
            if self._deferred is not None:
                raise DeferredInitializationError(
                    f"Parameter {self._name} deferred-init pending")
            raise RuntimeError(
                f"Parameter {self._name} has not been initialized. "
                "Call .initialize() on the Block first")
        if device is not None and device not in self._data_map:
            raise RuntimeError(
                f"Parameter {self._name} not initialized on {device}; "
                f"it lives on {list(self._data_map)}")

    # -- data access -------------------------------------------------------
    def data(self, ctx=None, device=None):
        """The parameter value on `device` (primary device by default).

        During CachedOp tracing returns the traced stand-in (the analog of
        the reference feeding param NDArrays as CachedOp inputs).
        """
        if self._traced_data is not None:
            return self._traced_data
        device = device if device is not None else ctx
        self._check_initialized(
            device if isinstance(device, Device) else None)
        if device is None:
            return self._data_map[self._ctx_list[0]]
        if not isinstance(device, Device):
            device = Device(device)
        if device not in self._data_map:
            raise RuntimeError(
                f"Parameter {self._name} not initialized on {device}")
        return self._data_map[device]

    def data_for(self, x):
        """Copy co-located with NDArray x (layers use this in forward)."""
        if self._traced_data is not None:
            return self._traced_data
        self._check_initialized()
        if len(self._data_map) == 1:
            return self._data_map[self._ctx_list[0]]
        dev = x.device
        return self._data_map.get(dev, self._data_map[self._ctx_list[0]])

    def logical_data(self, ctx=None, device=None):
        """The value in the parameter's LOGICAL layout (``self.shape``),
        undoing any persistent physical re-layout — what checkpoints and
        save_parameters serialize so files stay portable across
        MXTPU_LAYOUT settings."""
        arr = self.data(ctx=ctx, device=device)
        if self._layout_perm is None or self._traced_data is not None:
            return arr
        inv = tuple(int(i) for i in _np.argsort(self._layout_perm))
        return _wrap_out(jnp.transpose(arr._data, inv))

    def list_data(self):
        self._check_initialized()
        return [self._data_map[d] for d in self._ctx_list]

    def grad(self, ctx=None, device=None):
        device = device if device is not None else ctx
        self._check_initialized()
        if self.grad_req == "null":
            raise RuntimeError(
                f"Parameter {self._name} has grad_req='null'")
        if device is None:
            return self._grad_map[self._ctx_list[0]]
        if not isinstance(device, Device):
            device = Device(device)
        return self._grad_map[device]

    def list_grad(self):
        self._check_initialized()
        return [self._grad_map[d] for d in self._ctx_list]

    def list_ctx(self):
        self._check_initialized()
        return list(self._ctx_list)

    list_device = list_ctx

    def set_data(self, data):
        """Set value on all devices (reference: Parameter.set_data)."""
        if self._data_map is None:
            if self._deferred is not None:
                # deferred-init param: the incoming value fixes the shape
                self.shape = data.shape
                self._finish_deferred_init()
                self.set_data(data)
                return
            raise RuntimeError(
                f"Parameter {self._name} has not been initialized; call "
                ".initialize() before set_data (reference parity)")
        if not isinstance(data, NDArray):
            data = NDArray(jnp.asarray(data, self.dtype))
        src = data._data
        # set_data speaks the LOGICAL layout (checkpoints, user code);
        # convert to the persistent physical layout once, here, so NCHW
        # era files load bitwise onto re-laid-out parameters
        if self._layout_perm is not None:
            phys = tuple(self._shape[i] for i in self._layout_perm)
            if tuple(src.shape) == phys and phys != tuple(self._shape):
                pass  # already physical (internal caller)
            else:
                src = jnp.transpose(src, self._layout_perm)
        for d in self._ctx_list:
            arr = self._data_map[d]
            # honor the declared dtype, not the old buffer's — load with
            # dtype_source='saved' retypes the parameter before set_data
            arr._data = jnp.asarray(src, self.dtype or arr._data.dtype)
            arr._version += 1

    def zero_grad(self):
        if self._grad_map:
            for g in self._grad_map.values():
                g._data = jnp.zeros_like(g._data)
                g._version += 1
        self._sparse_row_hints = []

    def _record_sparse_rows(self, ids):
        """Called by sparse_grad layers during forward with the (concrete)
        row ids the lookup touched. Tracers are skipped — the hybridized
        path falls back to a dense update."""
        if self.grad_stype != "row_sparse" or self.grad_req == "null":
            return
        from .. import autograd as _ag

        if not _ag.is_recording():
            return   # eval/inference forwards must not skew the lazy rows
        import jax.core as _core

        if isinstance(ids, _core.Tracer):
            return
        self._sparse_row_hints.append(jnp.ravel(jnp.asarray(ids)))

    def _as_row_sparse_grad(self, g):
        """Dense grad buffer -> RowSparseNDArray over the rows touched
        since the last update. Fully on-device: fixed-size jnp.unique pads
        with the out-of-range index shape[0], which the optimizer's
        scatter drops (reference: row_sparse grad of Embedding,
        sparse.py:575). Returns the dense grad unchanged if no rows were
        recorded (e.g. hybridized forward)."""
        if not self._sparse_row_hints:
            return g
        from ..ndarray.sparse import RowSparseNDArray

        ids = (self._sparse_row_hints[0] if len(self._sparse_row_hints) == 1
               else jnp.concatenate(self._sparse_row_hints))
        self._sparse_row_hints = []
        n = g.shape[0]
        k = min(int(ids.size), int(n))
        uids = jnp.unique(ids.astype(jnp.int32), size=k, fill_value=n)
        return RowSparseNDArray(g._data[uids], uids, g.shape)

    def reset_ctx(self, ctx=None, device=None):
        device = device if device is not None else ctx
        devices = device if isinstance(device, (list, tuple)) else [device]
        devices = [d if isinstance(d, Device) else Device(d) for d in devices]
        self._check_initialized()
        master = self._data_map[self._ctx_list[0]]
        self._ctx_list = devices
        self._data_map = {d: master.copyto(d) for d in devices}
        if self.grad_req != "null":
            self._init_grad_buffers()

    reset_device = reset_ctx

    def cast(self, dtype):
        dtype = normalize_dtype(dtype)
        self.dtype = dtype
        if self._data_map is not None:
            for d, arr in self._data_map.items():
                arr._data = arr._data.astype(dtype)
                arr._version += 1
            for g in (self._grad_map or {}).values():
                g._data = g._data.astype(dtype)

    # misc
    def var(self):
        """Symbol variable for this parameter (reference: parameter.py
        var). The variable name is namespaced per parameter object (the
        reference uses a UUID) so two blocks' 'weight' params never
        alias in one graph; known shape is attached for inference."""
        from ..symbol.symbol import var as _sym_var

        if not hasattr(self, "_var_name") or self._var_name is None:
            try:
                self._var_name = f"{self.name}_{uuid.uuid4().hex[:8]}"
            except AttributeError:  # __slots__ without the field
                return _sym_var(f"{self.name}_{id(self):x}",
                                shape=self.shape)
        return _sym_var(self._var_name, shape=self.shape)


class Constant(Parameter):
    """Non-learnable constant parameter (reference: gluon Constant)."""

    def __init__(self, value, name="const"):
        if not isinstance(value, NDArray):
            value = NDArray(jnp.asarray(value))
        super().__init__(name=name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, differentiable=False,
                         init=init_mod.Constant(0.0))
        self._value = value

    def _finish_init(self, init, devices, default_init):  # noqa: ARG002
        self._ctx_list = list(devices)
        self._data_map = {d: self._value.copyto(d) for d in devices}
        self._grad_map = {}
        self._deferred = None
