"""Gluon utilities (reference: python/mxnet/gluon/utils.py — split/load
helpers, global-norm clipping, artifact download with checksum, hook
handles)."""
from __future__ import annotations

import hashlib
import math
import os

import numpy as _onp

from .. import numpy as _mxnp
from ..ndarray.ndarray import NDArray, apply_op

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download", "HookHandle", "shape_is_known"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split `data` into `num_slice` chunks along `batch_axis`
    (reference: utils.py:41). With even_split, the batch must divide
    evenly; otherwise the last slice carries the remainder."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {tuple(data.shape)} cannot be evenly split "
            f"into {num_slice} slices along axis {batch_axis}; set "
            "even_split=False or adjust the batch size")
    if num_slice == 1:
        return [data]
    # floor step; the LAST slice absorbs the remainder — always exactly
    # num_slice slices (the reference contract, so split_and_load maps
    # one slice per device)
    step = size // num_slice
    if step == 0:
        raise ValueError(
            f"batch of {size} cannot feed {num_slice} slices")
    slices = []
    for i in range(num_slice):
        start = i * step
        stop = size if i == num_slice - 1 else (i + 1) * step
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(start, stop)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split along the batch axis and place one slice per device
    (reference: utils.py:87)."""
    if not isinstance(data, NDArray):
        data = _mxnp.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_ctx(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_ctx(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale `arrays` in place so their joint L2 norm is at most
    `max_norm`; returns the pre-clip global norm (reference:
    utils.py:117).

    check_isfinite=True host-syncs and raises on a non-finite norm;
    False keeps the whole operation on-device and async (returns the
    norm as an NDArray) — a NaN norm then propagates NaN into the
    arrays, surfacing at the next host read, the documented trade."""
    if not arrays:
        raise ValueError("arrays is empty")
    import jax.numpy as jnp

    total = apply_op(
        lambda *xs: jnp.sqrt(sum(
            jnp.sum(jnp.square(x.astype(jnp.float32))) for x in xs)),
        *arrays, name="global_norm")
    # device-side scale: min(1, max_norm / norm) — no host sync needed
    scale = apply_op(
        lambda t: jnp.minimum(1.0, max_norm / (t + 1e-8)), total,
        name="clip_scale")
    if check_isfinite:
        norm = float(total.asnumpy())
        if not math.isfinite(norm):
            # reference (utils.py clip_global_norm): WARN and skip the
            # rescale — training code decides what to do with the step.
            # Attribution beyond the reference: one fused per-array
            # is-finite pass names WHICH arrays poisoned the norm.
            import warnings

            offenders = _nonfinite_offenders(arrays)
            detail = ""
            if offenders:
                i, a = offenders[0]
                detail = (
                    f"; first non-finite array: #{i} "
                    f"{a.shape}/{a.dtype}"
                    f" ({len(offenders)} of {len(arrays)} non-finite)")
            try:
                from ..observability import flight as _flight

                _flight.record(
                    "clip_nonfinite", norm=norm,
                    offenders=[i for i, _ in offenders],
                    arrays=len(arrays))
            except Exception:
                pass
            warnings.warn(
                f"nan or inf is detected. Clipping results will be "
                f"undefined (global norm = {norm}{detail})", stacklevel=2)
            return norm
        if norm > max_norm:
            for a in arrays:
                a *= scale
        return norm
    for a in arrays:
        a *= scale  # multiply by 1.0 when under the limit
    return total


def _nonfinite_offenders(arrays):
    """[(index, array)] of arrays holding non-finite values — one fused
    device pass + one host read (only runs on the already-failed path)."""
    import jax.numpy as jnp

    try:
        flags = apply_op(
            lambda *xs: jnp.stack([jnp.isfinite(x).all() for x in xs]),
            *arrays, name="isfinite_flags")
        finite = _onp.asarray(flags.asnumpy()).astype(bool)
        return [(i, a) for i, (a, ok) in enumerate(zip(arrays, finite))
                if not ok]
    except Exception:
        return []


def check_sha1(filename, sha1_hash):
    """True iff the file's sha1 matches (reference: utils.py:182)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):  # noqa: ARG001
    """Download `url` to `path` (reference: utils.py:274). This image has
    no network egress: if the target file already exists (pre-seeded) it
    is verified and returned; otherwise a clear error explains how to
    provide the file."""
    fname = path if path and not os.path.isdir(path or "") else \
        os.path.join(path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite:
        if sha1_hash and not check_sha1(fname, sha1_hash):
            raise OSError(f"{fname} exists but sha1 mismatch")
        return fname
    raise OSError(
        f"cannot download {url}: this environment has no network access. "
        f"Place the file at {fname} manually (sha1="
        f"{sha1_hash or 'unchecked'}).")


_hook_counter = [0]


class HookHandle:
    """Removable handle for registered hooks (reference: utils.py:398).
    Keys are a global counter, so the same callable can register under
    several handles without collision."""

    def __init__(self):
        self._hooks_dict = None
        self._id = None

    def attach(self, hooks_dict, hook):
        assert not self._hooks_dict, "already attached"
        _hook_counter[0] += 1
        self._id = _hook_counter[0]
        hooks_dict[self._id] = hook
        self._hooks_dict = hooks_dict

    def detach(self):
        if self._hooks_dict and self._id in self._hooks_dict:
            del self._hooks_dict[self._id]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.detach()


def shape_is_known(shape):
    """True iff no dimension is unknown (reference: utils.py:433)."""
    if shape is None:
        return False
    for d in shape:
        if d is None or d < 0:
            return False
    return True


def _as_list(obj):
    return obj if isinstance(obj, (list, tuple)) else [obj]
