"""mx.gluon.probability — distributions, transformations, stochastic blocks.

Reference surface: python/mxnet/gluon/probability/ (distributions/,
transformation/, block/). TPU re-design: all densities/samplers are pure
jax.numpy + jax.random (XLA-fused, reparameterized where the reference is),
with the framework's stateful-RNG facade supplying PRNG keys.
"""
from . import constraint  # noqa: F401
from . import stochastic_block as block  # noqa: F401  (reference path:
#                      gluon/probability/block/stochastic_block.py)
from . import distributions  # noqa: F401  (reference subpackage spelling)
from .constraint import *  # noqa: F401,F403
from .continuous import *  # noqa: F401,F403
from .discrete import *  # noqa: F401,F403
from .distribution import Distribution, ExponentialFamily  # noqa: F401
from .divergence import empirical_kl, kl_divergence, register_kl  # noqa: F401
from .utils import (  # noqa: F401
    cached_property,
    constraint_check,
    digamma,
    erf,
    erfinv,
    gammaln,
    logit2prob,
    prob2logit,
)
from .multivariate import *  # noqa: F401,F403
from .stochastic_block import StochasticBlock, StochasticSequential  # noqa: F401
from .transformation import *  # noqa: F401,F403
from .transformed_distribution import (  # noqa: F401
    Independent,
    TransformedDistribution,
)
