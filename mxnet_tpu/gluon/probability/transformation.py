"""Invertible transformations for TransformedDistribution and domain maps.

Reference surface: python/mxnet/gluon/probability/transformation/
transformation.py (Transformation, ComposeTransform, Exp/Affine/Power/
Sigmoid/Softmax/Abs transforms, TransformBlock) and domain_map.py
(biject_to / transform_to constraint→transformation registries).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.scipy import special as jsp

from ..block import HybridBlock
from . import constraint as C
from .utils import as_jax, sum_right_most, wrap

__all__ = ["Transformation", "TransformBlock", "ComposeTransform",
           "ExpTransform", "AffineTransform", "PowerTransform",
           "SigmoidTransform", "SoftmaxTransform", "AbsTransform",
           "biject_to", "transform_to", "domain_map"]


class Transformation:
    r"""Invertible transformation with computable log-det-Jacobian."""

    bijective = False
    event_dim = 0

    def __init__(self):
        self._inv = None

    @property
    def sign(self):
        """Sign of the Jacobian determinant (+1/-1 for monotone maps)."""
        raise NotImplementedError

    @property
    def inv(self):
        inv = self._inv
        if inv is None:
            inv = _InverseTransformation(self)
            self._inv = inv
        return inv

    def __call__(self, x):
        return wrap(self._forward_compute(jnp.asarray(as_jax(x))))

    def _inv_call(self, y):
        return wrap(self._inverse_compute(jnp.asarray(as_jax(y))))

    def _forward_compute(self, x):
        raise NotImplementedError

    def _inverse_compute(self, y):
        raise NotImplementedError

    def log_det_jacobian(self, x, y):
        r"""log |dy/dx| evaluated at (x, y=f(x))."""
        raise NotImplementedError


class _InverseTransformation(Transformation):
    def __init__(self, forward_transformation):
        super().__init__()
        self._forward = forward_transformation

    @property
    def inv(self):
        return self._forward

    @property
    def sign(self):
        return self._forward.sign

    @property
    def event_dim(self):
        return self._forward.event_dim

    def __call__(self, x):
        return self._forward._inv_call(x)

    def _inv_call(self, y):
        return self._forward(y)

    def log_det_jacobian(self, x, y):
        return wrap(-as_jax(self._forward.log_det_jacobian(y, x)))


class TransformBlock(Transformation, HybridBlock):
    """Transformation that is also a HybridBlock, so it can carry
    learnable parameters (normalizing-flow layers)."""

    def __init__(self, *args, **kwargs):
        # HybridBlock must init first: Block.__setattr__ needs _children
        # to exist before Transformation sets self._inv
        HybridBlock.__init__(self, *args, **kwargs)
        Transformation.__init__(self)


class ComposeTransform(Transformation):
    def __init__(self, parts):
        super().__init__()
        self._parts = list(parts)

    def _forward_compute(self, x):
        for t in self._parts:
            x = as_jax(t(x))
        return x

    def _inverse_compute(self, y):
        for t in reversed(self._parts):
            y = as_jax(t._inv_call(y))
        return y

    @property
    def sign(self):
        s = 1
        for t in self._parts:
            s = s * t.sign
        return s

    @property
    def event_dim(self):
        return max(t.event_dim for t in self._parts) if self._parts else 0

    @property
    def inv(self):
        inv = self._inv
        if inv is None:
            inv = ComposeTransform([t.inv for t in reversed(self._parts)])
            inv._inv = self
            self._inv = inv
        return inv

    def log_det_jacobian(self, x, y):  # noqa: ARG002
        x = jnp.asarray(as_jax(x))
        result = 0.0
        event_dim = self.event_dim
        for t in self._parts:
            y_t = as_jax(t(x))
            ldj = as_jax(t.log_det_jacobian(x, y_t))
            result = result + sum_right_most(ldj,
                                             event_dim - t.event_dim)
            x = y_t
        return wrap(result)


class ExpTransform(Transformation):
    bijective = True
    sign = 1

    def _forward_compute(self, x):
        return jnp.exp(x)

    def _inverse_compute(self, y):
        return jnp.log(y)

    def log_det_jacobian(self, x, y):  # noqa: ARG002
        return wrap(jnp.asarray(as_jax(x)))


class AffineTransform(Transformation):
    bijective = True

    def __init__(self, loc, scale, event_dim=0):
        super().__init__()
        self.loc = jnp.asarray(as_jax(loc), jnp.float32)
        self.scale = jnp.asarray(as_jax(scale), jnp.float32)
        self.event_dim = event_dim

    def _forward_compute(self, x):
        return self.loc + self.scale * x

    def _inverse_compute(self, y):
        return (y - self.loc) / self.scale

    def log_det_jacobian(self, x, y):  # noqa: ARG002
        x = jnp.asarray(as_jax(x))
        ldj = jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)
        return wrap(sum_right_most(ldj, self.event_dim))

    @property
    def sign(self):
        return jnp.sign(self.scale)


class PowerTransform(Transformation):
    bijective = True
    sign = 1

    def __init__(self, exponent):
        super().__init__()
        self.exponent = jnp.asarray(as_jax(exponent), jnp.float32)

    def _forward_compute(self, x):
        return jnp.power(x, self.exponent)

    def _inverse_compute(self, y):
        return jnp.power(y, 1.0 / self.exponent)

    def log_det_jacobian(self, x, y):
        x = jnp.asarray(as_jax(x))
        y = jnp.asarray(as_jax(y))
        return wrap(jnp.log(jnp.abs(self.exponent * y / x)))


class SigmoidTransform(Transformation):
    bijective = True
    sign = 1

    def _forward_compute(self, x):
        return 1.0 / (1.0 + jnp.exp(-x))

    def _inverse_compute(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def log_det_jacobian(self, x, y):  # noqa: ARG002
        x = jnp.asarray(as_jax(x))
        return wrap(-jnp.logaddexp(0.0, x) - jnp.logaddexp(0.0, -x))


class SoftmaxTransform(Transformation):
    """Coordinate-wise exp then normalize — not bijective; log-det
    undefined (matches reference SoftmaxTransform)."""

    event_dim = 1

    def _forward_compute(self, x):
        z = x - jnp.max(x, axis=-1, keepdims=True)
        e = jnp.exp(z)
        return e / jnp.sum(e, axis=-1, keepdims=True)

    def _inverse_compute(self, y):
        return jnp.log(y)


class AbsTransform(Transformation):
    def _forward_compute(self, x):
        return jnp.abs(x)

    def _inverse_compute(self, y):
        return y


class _StickBreakingTransform(Transformation):
    """Real^{K-1} → simplex^K, used by transform_to(Simplex)."""

    bijective = True
    event_dim = 1

    def _forward_compute(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = 1.0 / (1.0 + jnp.exp(-(x - jnp.log(offset))))
        z_cumprod = jnp.cumprod(1 - z, axis=-1)
        pad_z = jnp.pad(z, [(0, 0)] * (z.ndim - 1) + [(0, 1)],
                        constant_values=1.0)
        pad_cum = jnp.pad(z_cumprod, [(0, 0)] * (z.ndim - 1) + [(1, 0)],
                          constant_values=1.0)
        return pad_z * pad_cum

    def _inverse_compute(self, y):
        y_crop = y[..., :-1]
        offset = y.shape[-1] - 1 - jnp.arange(y_crop.shape[-1],
                                              dtype=y.dtype)
        rest = 1 - jnp.cumsum(y_crop, axis=-1)
        prev_rest = jnp.pad(rest[..., :-1],
                            [(0, 0)] * (y.ndim - 1) + [(1, 0)],
                            constant_values=1.0)
        z = y_crop / prev_rest
        return jnp.log(z / (1 - z)) + jnp.log(offset)

    def log_det_jacobian(self, x, y):  # noqa: ARG002
        # dy_k/dx_k = z_k (1-z_k) prod_{j<k}(1-z_j), triangular Jacobian:
        # log|det| = sum_k [log z_k + log(1-z_k) + sum_{j<k} log(1-z_j)]
        x = jnp.asarray(as_jax(x))
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        t = x - jnp.log(offset)
        # log z = -softplus(-t); log(1-z) = -softplus(t)
        log_z = -jnp.logaddexp(0.0, -t)
        log_1mz = -jnp.logaddexp(0.0, t)
        prev_cum = jnp.pad(jnp.cumsum(log_1mz[..., :-1], axis=-1),
                           [(0, 0)] * (x.ndim - 1) + [(1, 0)])
        return wrap(jnp.sum(log_z + log_1mz + prev_cum, axis=-1))


# -- domain maps (reference: transformation/domain_map.py) ---------------

def domain_map(constraint):
    """Return a Transformation mapping unconstrained reals onto the
    support described by `constraint`."""
    if isinstance(constraint, C.Real):
        class _Identity(Transformation):
            bijective = True
            sign = 1

            def _forward_compute(self, x):
                return x

            def _inverse_compute(self, y):
                return y

            def log_det_jacobian(self, x, y):  # noqa: ARG002
                return wrap(jnp.zeros_like(jnp.asarray(as_jax(x))))
        return _Identity()
    if isinstance(constraint, (C.Positive, C.NonNegative)):
        return ExpTransform()
    if isinstance(constraint, C.GreaterThan):
        return ComposeTransform(
            [ExpTransform(), AffineTransform(constraint.lower, 1.0)])
    if isinstance(constraint, C.LessThan):
        return ComposeTransform(
            [ExpTransform(), AffineTransform(constraint.upper, -1.0)])
    if isinstance(constraint, C.UnitInterval):
        return SigmoidTransform()
    if isinstance(constraint, C.Interval):
        return ComposeTransform(
            [SigmoidTransform(),
             AffineTransform(constraint.lower,
                             constraint.upper - constraint.lower)])
    if isinstance(constraint, C.Simplex):
        return _StickBreakingTransform()
    raise NotImplementedError(
        f"No domain map registered for {type(constraint).__name__}")


biject_to = domain_map
transform_to = domain_map
