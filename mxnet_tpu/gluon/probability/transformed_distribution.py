"""TransformedDistribution and Independent.

Reference surface: distributions/transformed_distribution.py (log_prob via
inverse transforms + log-det-Jacobian chain; sample pushes base samples
through the transforms) and independent.py (reinterpret rightmost batch
dims as event dims).
"""
from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution
from .transformation import Transformation
from .utils import as_jax, sum_right_most, wrap

__all__ = ["TransformedDistribution", "Independent"]


class TransformedDistribution(Distribution):
    def __init__(self, base_dist, transforms, validate_args=None):
        self.base_dist = base_dist
        if isinstance(transforms, Transformation):
            transforms = [transforms]
        self.transforms = list(transforms)
        event_dim = max([base_dist.event_dim or 0]
                        + [t.event_dim for t in self.transforms])
        super().__init__(event_dim=event_dim, validate_args=validate_args)

    @property
    def has_grad(self):
        return self.base_dist.has_grad

    def _batch_shape(self):
        return self.base_dist._batch_shape()

    def sample(self, size=None):
        x = self.base_dist.sample(size)
        for t in self.transforms:
            x = t(x)
        return x

    def sample_n(self, size):
        x = self.base_dist.sample_n(size)
        for t in self.transforms:
            x = t(x)
        return x

    def log_prob(self, value):
        log_prob = 0.0
        y = jnp.asarray(as_jax(value))
        event_dim = self.event_dim
        # walk the transform chain backwards, accumulating -log|J|
        for t in reversed(self.transforms):
            x = as_jax(t._inv_call(y))
            ldj = as_jax(t.log_det_jacobian(x, y))
            log_prob = log_prob - sum_right_most(ldj,
                                                 event_dim - t.event_dim)
            y = x
        base_lp = as_jax(self.base_dist.log_prob(wrap(y)))
        log_prob = log_prob + sum_right_most(
            base_lp, event_dim - (self.base_dist.event_dim or 0))
        return wrap(log_prob)

    def cdf(self, value):
        y = jnp.asarray(as_jax(value))
        sign = 1
        for t in reversed(self.transforms):
            y = as_jax(t._inv_call(y))
            sign = sign * t.sign
        base_cdf = as_jax(self.base_dist.cdf(wrap(y)))
        return wrap(sign * (base_cdf - 0.5) + 0.5)

    def icdf(self, value):
        p = jnp.asarray(as_jax(value))
        sign = 1
        for t in self.transforms:
            sign = sign * t.sign
        p = sign * (p - 0.5) + 0.5
        x = self.base_dist.icdf(wrap(p))
        for t in self.transforms:
            x = t(x)
        return x


class Independent(Distribution):
    r"""Reinterpret the rightmost `reinterpreted_batch_ndims` batch dims of
    `base` as event dims: log_prob sums over them."""

    def __init__(self, base, reinterpreted_batch_ndims,
                 validate_args=None):
        self.base_dist = base
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)
        event_dim = (base.event_dim or 0) + self.reinterpreted_batch_ndims
        super().__init__(event_dim=event_dim, validate_args=validate_args)

    @property
    def has_grad(self):
        return self.base_dist.has_grad

    def _batch_shape(self):
        b = self.base_dist._batch_shape()
        return b[:len(b) - self.reinterpreted_batch_ndims]

    def log_prob(self, value):
        lp = as_jax(self.base_dist.log_prob(value))
        return wrap(sum_right_most(lp, self.reinterpreted_batch_ndims))

    def sample(self, size=None):
        if size is None:
            return self.base_dist.sample(None)
        size = self._size(size)
        tail = self.base_dist._batch_shape()[
            len(self.base_dist._batch_shape())
            - self.reinterpreted_batch_ndims:]
        return self.base_dist.sample(tuple(size) + tuple(tail))

    def sample_n(self, size):
        n = self._size(size) or ()
        return self.base_dist.sample_n(n)

    @property
    def mean(self):
        return self.base_dist.mean

    @property
    def variance(self):
        return self.base_dist.variance

    def entropy(self):
        ent = as_jax(self.base_dist.entropy())
        return wrap(sum_right_most(ent, self.reinterpreted_batch_ndims))
