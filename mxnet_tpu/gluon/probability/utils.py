"""Shared helpers for gluon.probability.

Reference surface: python/mxnet/gluon/probability/distributions/utils.py
(prob2logit/logit2prob/getF/cached_property). TPU re-design: distributions
compute directly on jax arrays (XLA fuses the elementwise math); the
NDArray wrapper is applied at the public API boundary.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...ndarray.ndarray import NDArray

__all__ = ["prob2logit", "logit2prob", "cached_property", "as_jax", "wrap",
           "sum_right_most", "constraint_check", "digamma", "gammaln",
           "erf", "erfinv"]


def as_jax(x):
    """Unwrap NDArray / python scalar to a jax value."""
    if isinstance(x, NDArray):
        return x._data
    return x


def wrap(x):
    """Wrap a jax array as the framework NDArray."""
    return NDArray(jnp.asarray(x))


def prob2logit(prob, binary=True):
    """Convert probability to logit (log-odds for binary, log-prob otherwise)."""
    prob = jnp.asarray(as_jax(prob))
    eps = jnp.finfo(jnp.result_type(prob, jnp.float32)).tiny
    prob = jnp.clip(prob, eps, 1.0 - eps if binary else 1.0)
    if binary:
        return jnp.log(prob) - jnp.log1p(-prob)
    return jnp.log(prob)


def logit2prob(logit, binary=True):
    """Convert logit back to probability."""
    logit = jnp.asarray(as_jax(logit))
    if binary:
        return 1.0 / (1.0 + jnp.exp(-logit))
    return jnp.exp(logit - jnp.max(logit, axis=-1, keepdims=True)) / jnp.sum(
        jnp.exp(logit - jnp.max(logit, axis=-1, keepdims=True)), axis=-1,
        keepdims=True)


def sum_right_most(x, ndim):
    """Sum over the rightmost `ndim` axes (event-dim reduction)."""
    if ndim == 0:
        return x
    return jnp.sum(x, axis=tuple(range(-ndim, 0)))


class cached_property:
    """Descriptor caching a derived parameter on first access
    (reference: distributions/utils.py cached_property)."""

    def __init__(self, func):
        self._func = func
        self.__doc__ = getattr(func, "__doc__", None)
        self._name = func.__name__

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        value = self._func(obj)
        obj.__dict__[self._name] = value
        return value


# -- reference op getters (distributions/utils.py:34-99: each returns a
# callable usable on scalars AND tensors) --------------------------------

def constraint_check():
    from ... import npx

    def _check(condition, err_msg):
        if isinstance(condition, bool):
            if not condition:
                raise ValueError(err_msg)
            return 1.0
        return npx.constraint_check(condition, err_msg)

    return _check


def _special(jsp_name):
    def getter():
        import jax.scipy.special as jsp

        fn = getattr(jsp, jsp_name)

        def compute(value):
            from numbers import Number

            if isinstance(value, Number):
                return float(fn(value))
            return wrap(fn(jnp.asarray(as_jax(value))))

        return compute

    return getter


digamma = _special("digamma")
gammaln = _special("gammaln")
erf = _special("erf")
erfinv = _special("erfinv")
