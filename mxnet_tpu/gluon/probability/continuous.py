"""Continuous univariate distributions.

Reference surface (one file per distribution under
python/mxnet/gluon/probability/distributions/): normal.py, laplace.py,
cauchy.py, half_cauchy.py, half_normal.py, uniform.py, exponential.py,
gamma.py, beta.py, chi2.py, fishersnedecor.py, studentT.py, gumbel.py,
weibull.py, pareto.py. Parameterizations match the reference (e.g.
Gamma(shape, scale), Weibull(concentration, scale), Pareto(alpha, scale),
Exponential(scale)).

TPU re-design: samplers use jax.random primitives (threefry counters, no
per-device mutable RNG state); reparameterized (pathwise-grad) samplers are
flagged has_grad=True.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from . import constraint as C
from .distribution import Distribution, ExponentialFamily
from .utils import as_jax, wrap

__all__ = ["Normal", "Laplace", "Cauchy", "HalfCauchy", "HalfNormal",
           "Uniform", "Exponential", "Gamma", "Beta", "Chi2",
           "FisherSnedecor", "StudentT", "Gumbel", "Weibull", "Pareto"]


class _LocScale(Distribution):
    """Shared machinery for two-parameter families broadcast to one batch
    shape."""

    _params = ("loc", "scale")

    def __init__(self, p0, p1, validate_args=None):
        a = jnp.asarray(as_jax(p0), jnp.float32)
        b = jnp.asarray(as_jax(p1), jnp.float32)
        shape = jnp.broadcast_shapes(a.shape, b.shape)
        setattr(self, self._params[0], jnp.broadcast_to(a, shape))
        setattr(self, self._params[1], jnp.broadcast_to(b, shape))
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return getattr(self, self._params[0]).shape

    def _extended(self, size):
        size = self._size(size)
        return self._batch_shape() if size is None else size

    def broadcast_to(self, batch_shape):
        new = self.__new__(type(self))
        batch_shape = tuple(batch_shape)
        for p in self._params:
            setattr(new, p, jnp.broadcast_to(getattr(self, p), batch_shape))
        new.event_dim = self.event_dim
        new._validate_args = self._validate_args
        return new


class Normal(_LocScale, ExponentialFamily):
    r"""Gaussian with mean `loc`, standard deviation `scale`."""

    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real(), "scale": C.Positive()}

    def __init__(self, loc=0.0, scale=1.0, validate_args=None):
        super().__init__(loc, scale, validate_args)

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        v = jnp.asarray(as_jax(value))
        var = self.scale ** 2
        return wrap(-((v - self.loc) ** 2) / (2 * var)
                    - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def sample(self, size=None):
        shape = self._extended(size)
        eps = jax.random.normal(self._key(), shape)
        return wrap(self.loc + eps * self.scale)

    def cdf(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(0.5 * (1 + jsp.erf((v - self.loc)
                                       / (self.scale * math.sqrt(2)))))

    def icdf(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(self.loc + self.scale * jsp.ndtri(v))

    @property
    def mean(self):
        return wrap(self.loc)

    @property
    def variance(self):
        return wrap(self.scale ** 2)

    def entropy(self):
        return wrap(0.5 + 0.5 * math.log(2 * math.pi)
                    + jnp.log(self.scale))

    @property
    def _natural_params(self):
        return (self.loc / self.scale ** 2, -0.5 / self.scale ** 2)

    def _log_normalizer(self, x, y):
        return -0.25 * x ** 2 / y + 0.5 * jnp.log(-math.pi / y)

    def _mean_carrier_measure(self):
        return 0.0


class Laplace(_LocScale):
    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real(), "scale": C.Positive()}

    def __init__(self, loc=0.0, scale=1.0, validate_args=None):
        super().__init__(loc, scale, validate_args)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(-jnp.abs(v - self.loc) / self.scale
                    - jnp.log(2 * self.scale))

    def sample(self, size=None):
        shape = self._extended(size)
        u = jax.random.uniform(self._key(), shape, minval=-0.5 + 1e-7,
                               maxval=0.5)
        return wrap(self.loc - self.scale * jnp.sign(u)
                    * jnp.log1p(-2 * jnp.abs(u)))

    def cdf(self, value):
        v = jnp.asarray(as_jax(value))
        z = (v - self.loc) / self.scale
        return wrap(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, value):
        p = jnp.asarray(as_jax(value))
        term = p - 0.5
        return wrap(self.loc - self.scale * jnp.sign(term)
                    * jnp.log1p(-2 * jnp.abs(term)))

    @property
    def mean(self):
        return wrap(self.loc)

    @property
    def variance(self):
        return wrap(2 * self.scale ** 2)

    def entropy(self):
        return wrap(1 + jnp.log(2 * self.scale))


class Cauchy(_LocScale):
    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real(), "scale": C.Positive()}

    def __init__(self, loc=0.0, scale=1.0, validate_args=None):
        super().__init__(loc, scale, validate_args)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(-math.log(math.pi) - jnp.log(self.scale)
                    - jnp.log1p(((v - self.loc) / self.scale) ** 2))

    def sample(self, size=None):
        shape = self._extended(size)
        u = jax.random.uniform(self._key(), shape, minval=1e-7,
                               maxval=1.0 - 1e-7)
        return wrap(self.loc + self.scale * jnp.tan(math.pi * (u - 0.5)))

    def cdf(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(jnp.arctan((v - self.loc) / self.scale) / math.pi + 0.5)

    def icdf(self, value):
        p = jnp.asarray(as_jax(value))
        return wrap(self.loc + self.scale * jnp.tan(math.pi * (p - 0.5)))

    @property
    def mean(self):
        return wrap(jnp.full(self._batch_shape(), jnp.nan))

    @property
    def variance(self):
        return wrap(jnp.full(self._batch_shape(), jnp.nan))

    def entropy(self):
        return wrap(math.log(4 * math.pi) + jnp.log(self.scale))


class _HalfOf(Distribution):
    """|X| for a symmetric zero-located base distribution."""

    _base_cls = None
    support = C.Positive()
    arg_constraints = {"scale": C.Positive()}
    has_grad = True

    def __init__(self, scale=1.0, validate_args=None):
        self.scale = jnp.asarray(as_jax(scale), jnp.float32)
        self._base = self._base_cls(0.0, self.scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self._base._batch_shape()

    def broadcast_to(self, batch_shape):
        return type(self)(jnp.broadcast_to(self.scale, tuple(batch_shape)))

    def sample(self, size=None):
        return wrap(jnp.abs(as_jax(self._base.sample(size))))

    def log_prob(self, value):
        return wrap(as_jax(self._base.log_prob(value)) + math.log(2))

    def cdf(self, value):
        return wrap(2 * as_jax(self._base.cdf(value)) - 1)

    def icdf(self, value):
        p = jnp.asarray(as_jax(value))
        return self._base.icdf((p + 1) / 2)


class HalfCauchy(_HalfOf):
    _base_cls = Cauchy

    def entropy(self):
        return wrap(as_jax(self._base.entropy()) - math.log(2))


class HalfNormal(_HalfOf):
    _base_cls = Normal

    @property
    def mean(self):
        return wrap(self.scale * math.sqrt(2 / math.pi))

    @property
    def variance(self):
        return wrap(self.scale ** 2 * (1 - 2 / math.pi))

    def entropy(self):
        return wrap(as_jax(self._base.entropy()) - math.log(2))


class Uniform(_LocScale):
    has_grad = True
    _params = ("low", "high")
    arg_constraints = {"low": C.dependent, "high": C.dependent}

    def __init__(self, low=0.0, high=1.0, validate_args=None):
        super().__init__(low, high, validate_args)

    @property
    def support(self):
        return C.Interval(self.low, self.high)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        inside = (v >= self.low) & (v <= self.high)
        lp = -jnp.log(self.high - self.low)
        return wrap(jnp.where(inside, lp, -jnp.inf))

    def sample(self, size=None):
        shape = self._extended(size)
        u = jax.random.uniform(self._key(), shape)
        return wrap(self.low + u * (self.high - self.low))

    def cdf(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(jnp.clip((v - self.low) / (self.high - self.low), 0, 1))

    def icdf(self, value):
        p = jnp.asarray(as_jax(value))
        return wrap(self.low + p * (self.high - self.low))

    @property
    def mean(self):
        return wrap((self.low + self.high) / 2)

    @property
    def variance(self):
        return wrap((self.high - self.low) ** 2 / 12)

    def entropy(self):
        return wrap(jnp.log(self.high - self.low))


class Exponential(ExponentialFamily):
    r"""Exponential with **scale** parameter (mean), matching the reference
    (distributions/exponential.py:43 `__init__(self, scale=1.0)`)."""

    has_grad = True
    support = C.Positive()
    arg_constraints = {"scale": C.Positive()}

    def __init__(self, scale=1.0, validate_args=None):
        self.scale = jnp.asarray(as_jax(scale), jnp.float32)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.scale.shape

    def broadcast_to(self, batch_shape):
        return Exponential(jnp.broadcast_to(self.scale, tuple(batch_shape)))

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(-v / self.scale - jnp.log(self.scale))

    def sample(self, size=None):
        size = self._size(size)
        shape = self.scale.shape if size is None else size
        e = jax.random.exponential(self._key(), shape)
        return wrap(e * self.scale)

    def sample_n(self, size):
        n = self._size(size) or ()
        return self.sample(tuple(n) + self.scale.shape)

    def cdf(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(-jnp.expm1(-v / self.scale))

    def icdf(self, value):
        p = jnp.asarray(as_jax(value))
        return wrap(-self.scale * jnp.log1p(-p))

    @property
    def mean(self):
        return wrap(self.scale)

    @property
    def variance(self):
        return wrap(self.scale ** 2)

    def entropy(self):
        return wrap(1 + jnp.log(self.scale))

    @property
    def _natural_params(self):
        return (-1.0 / self.scale,)

    def _log_normalizer(self, x):
        return -jnp.log(-x)

    def _mean_carrier_measure(self):
        return 0.0


class Gamma(Distribution):
    r"""Gamma(shape=α, scale=θ) — reference parameterization
    (distributions/gamma.py:48)."""

    has_grad = True  # jax.random.gamma has implicit-reparam gradients
    support = C.Positive()
    arg_constraints = {"shape": C.Positive(), "scale": C.Positive()}

    def __init__(self, shape, scale=1.0, validate_args=None):
        a = jnp.asarray(as_jax(shape), jnp.float32)
        s = jnp.asarray(as_jax(scale), jnp.float32)
        bshape = jnp.broadcast_shapes(a.shape, s.shape)
        self.shape = jnp.broadcast_to(a, bshape)
        self.scale = jnp.broadcast_to(s, bshape)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.shape.shape

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape)
        return Gamma(jnp.broadcast_to(self.shape, b),
                     jnp.broadcast_to(self.scale, b))

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        a = self.shape
        return wrap((a - 1) * jnp.log(v) - v / self.scale
                    - jsp.gammaln(a) - a * jnp.log(self.scale))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        g = jax.random.gamma(self._key(), self.shape, shape)
        return wrap(g * self.scale)

    @property
    def mean(self):
        return wrap(self.shape * self.scale)

    @property
    def variance(self):
        return wrap(self.shape * self.scale ** 2)

    def entropy(self):
        a = self.shape
        return wrap(a + jnp.log(self.scale) + jsp.gammaln(a)
                    + (1 - a) * jsp.digamma(a))


class Chi2(Gamma):
    r"""Chi-squared(df) == Gamma(df/2, scale=2)."""

    arg_constraints = {"df": C.Positive()}

    def __init__(self, df, validate_args=None):
        df = jnp.asarray(as_jax(df), jnp.float32)
        super().__init__(df / 2, 2.0, validate_args)

    @property
    def df(self):
        return wrap(self.shape * 2)

    def broadcast_to(self, batch_shape):
        return Chi2(jnp.broadcast_to(self.shape * 2, tuple(batch_shape)))


class Beta(Distribution):
    has_grad = True
    support = C.UnitInterval()
    arg_constraints = {"alpha": C.Positive(), "beta": C.Positive()}

    def __init__(self, alpha, beta, validate_args=None):
        a = jnp.asarray(as_jax(alpha), jnp.float32)
        b = jnp.asarray(as_jax(beta), jnp.float32)
        shape = jnp.broadcast_shapes(a.shape, b.shape)
        self.alpha = jnp.broadcast_to(a, shape)
        self.beta = jnp.broadcast_to(b, shape)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.alpha.shape

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape)
        return Beta(jnp.broadcast_to(self.alpha, b),
                    jnp.broadcast_to(self.beta, b))

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(jsp.xlogy(self.alpha - 1, v)
                    + jsp.xlogy(self.beta - 1, 1 - v)
                    - jsp.betaln(self.alpha, self.beta))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        return wrap(jax.random.beta(self._key(), self.alpha, self.beta,
                                    shape))

    @property
    def mean(self):
        return wrap(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        t = self.alpha + self.beta
        return wrap(self.alpha * self.beta / (t ** 2 * (t + 1)))

    def entropy(self):
        a, b = self.alpha, self.beta
        return wrap(jsp.betaln(a, b) - (a - 1) * jsp.digamma(a)
                    - (b - 1) * jsp.digamma(b)
                    + (a + b - 2) * jsp.digamma(a + b))


class FisherSnedecor(Distribution):
    r"""F-distribution(df1, df2) — ratio of scaled chi-squares."""

    support = C.Positive()
    arg_constraints = {"df1": C.Positive(), "df2": C.Positive()}

    def __init__(self, df1, df2, validate_args=None):
        a = jnp.asarray(as_jax(df1), jnp.float32)
        b = jnp.asarray(as_jax(df2), jnp.float32)
        shape = jnp.broadcast_shapes(a.shape, b.shape)
        self.df1 = jnp.broadcast_to(a, shape)
        self.df2 = jnp.broadcast_to(b, shape)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.df1.shape

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape)
        return FisherSnedecor(jnp.broadcast_to(self.df1, b),
                              jnp.broadcast_to(self.df2, b))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        k1, k2 = jax.random.split(self._key())
        g1 = jax.random.gamma(k1, self.df1 / 2, shape) / (self.df1 / 2)
        g2 = jax.random.gamma(k2, self.df2 / 2, shape) / (self.df2 / 2)
        return wrap(g1 / g2)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        d1, d2 = self.df1, self.df2
        return wrap(0.5 * d1 * jnp.log(d1) + 0.5 * d2 * jnp.log(d2)
                    + (0.5 * d1 - 1) * jnp.log(v)
                    - 0.5 * (d1 + d2) * jnp.log(d2 + d1 * v)
                    - jsp.betaln(0.5 * d1, 0.5 * d2))

    @property
    def mean(self):
        m = self.df2 / (self.df2 - 2)
        return wrap(jnp.where(self.df2 > 2, m, jnp.nan))

    @property
    def variance(self):
        d1, d2 = self.df1, self.df2
        v = 2 * d2 ** 2 * (d1 + d2 - 2) / (d1 * (d2 - 2) ** 2 * (d2 - 4))
        return wrap(jnp.where(d2 > 4, v, jnp.nan))


class StudentT(Distribution):
    has_grad = True
    support = C.Real()
    arg_constraints = {"df": C.Positive(), "loc": C.Real(),
                       "scale": C.Positive()}

    def __init__(self, df, loc=0.0, scale=1.0, validate_args=None):
        d = jnp.asarray(as_jax(df), jnp.float32)
        l = jnp.asarray(as_jax(loc), jnp.float32)
        s = jnp.asarray(as_jax(scale), jnp.float32)
        shape = jnp.broadcast_shapes(d.shape, l.shape, s.shape)
        self.df = jnp.broadcast_to(d, shape)
        self.loc = jnp.broadcast_to(l, shape)
        self.scale = jnp.broadcast_to(s, shape)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.df.shape

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape)
        return StudentT(jnp.broadcast_to(self.df, b),
                        jnp.broadcast_to(self.loc, b),
                        jnp.broadcast_to(self.scale, b))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        t = jax.random.t(self._key(), self.df, shape)
        return wrap(self.loc + self.scale * t)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        d = self.df
        z = (v - self.loc) / self.scale
        return wrap(jsp.gammaln((d + 1) / 2) - jsp.gammaln(d / 2)
                    - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                    - (d + 1) / 2 * jnp.log1p(z ** 2 / d))

    @property
    def mean(self):
        return wrap(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        d = self.df
        v = self.scale ** 2 * d / (d - 2)
        return wrap(jnp.where(d > 2, v,
                              jnp.where(d > 1, jnp.inf, jnp.nan)))

    def entropy(self):
        d = self.df
        return wrap((d + 1) / 2 * (jsp.digamma((d + 1) / 2)
                                   - jsp.digamma(d / 2))
                    + 0.5 * jnp.log(d) + jsp.betaln(d / 2, 0.5)
                    + jnp.log(self.scale))


_EULER = 0.57721566490153286060


class Gumbel(_LocScale):
    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real(), "scale": C.Positive()}

    def __init__(self, loc=0.0, scale=1.0, validate_args=None):
        super().__init__(loc, scale, validate_args)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        z = (v - self.loc) / self.scale
        return wrap(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def sample(self, size=None):
        shape = self._extended(size)
        g = jax.random.gumbel(self._key(), shape)
        return wrap(self.loc + self.scale * g)

    def cdf(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(jnp.exp(-jnp.exp(-(v - self.loc) / self.scale)))

    def icdf(self, value):
        p = jnp.asarray(as_jax(value))
        return wrap(self.loc - self.scale * jnp.log(-jnp.log(p)))

    @property
    def mean(self):
        return wrap(self.loc + self.scale * _EULER)

    @property
    def variance(self):
        return wrap(math.pi ** 2 / 6 * self.scale ** 2)

    def entropy(self):
        return wrap(jnp.log(self.scale) + 1 + _EULER)


class Weibull(Distribution):
    r"""Weibull(concentration=k, scale=λ) — reference parameterization
    (distributions/weibull.py:49)."""

    has_grad = True
    support = C.Positive()
    arg_constraints = {"concentration": C.Positive(), "scale": C.Positive()}

    def __init__(self, concentration, scale=1.0, validate_args=None):
        k = jnp.asarray(as_jax(concentration), jnp.float32)
        s = jnp.asarray(as_jax(scale), jnp.float32)
        shape = jnp.broadcast_shapes(k.shape, s.shape)
        self.concentration = jnp.broadcast_to(k, shape)
        self.scale = jnp.broadcast_to(s, shape)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.concentration.shape

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape)
        return Weibull(jnp.broadcast_to(self.concentration, b),
                       jnp.broadcast_to(self.scale, b))

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        k, s = self.concentration, self.scale
        return wrap(jnp.log(k / s) + (k - 1) * jnp.log(v / s)
                    - (v / s) ** k)

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        e = jax.random.exponential(self._key(), shape)
        return wrap(self.scale * e ** (1 / self.concentration))

    def cdf(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(-jnp.expm1(-(v / self.scale) ** self.concentration))

    def icdf(self, value):
        p = jnp.asarray(as_jax(value))
        return wrap(self.scale
                    * (-jnp.log1p(-p)) ** (1 / self.concentration))

    @property
    def mean(self):
        k = self.concentration
        return wrap(self.scale * jnp.exp(jsp.gammaln(1 + 1 / k)))

    @property
    def variance(self):
        k = self.concentration
        g1 = jnp.exp(jsp.gammaln(1 + 1 / k))
        g2 = jnp.exp(jsp.gammaln(1 + 2 / k))
        return wrap(self.scale ** 2 * (g2 - g1 ** 2))

    def entropy(self):
        k = self.concentration
        return wrap(_EULER * (1 - 1 / k) + jnp.log(self.scale / k) + 1)


class Pareto(Distribution):
    r"""Pareto(alpha, scale) — reference parameterization
    (distributions/pareto.py:47): support [scale, inf)."""

    arg_constraints = {"alpha": C.Positive(), "scale": C.Positive()}

    @property
    def support(self):
        return C.GreaterThanEq(self.scale)

    def __init__(self, alpha, scale=1.0, validate_args=None):
        a = jnp.asarray(as_jax(alpha), jnp.float32)
        s = jnp.asarray(as_jax(scale), jnp.float32)
        shape = jnp.broadcast_shapes(a.shape, s.shape)
        self.alpha = jnp.broadcast_to(a, shape)
        self.scale = jnp.broadcast_to(s, shape)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.alpha.shape

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape)
        return Pareto(jnp.broadcast_to(self.alpha, b),
                      jnp.broadcast_to(self.scale, b))

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        lp = (jnp.log(self.alpha) + self.alpha * jnp.log(self.scale)
              - (self.alpha + 1) * jnp.log(v))
        return wrap(jnp.where(v >= self.scale, lp, -jnp.inf))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        e = jax.random.exponential(self._key(), shape)
        return wrap(self.scale * jnp.exp(e / self.alpha))

    def cdf(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(jnp.where(v >= self.scale,
                              1 - (self.scale / v) ** self.alpha, 0.0))

    def icdf(self, value):
        p = jnp.asarray(as_jax(value))
        return wrap(self.scale * (1 - p) ** (-1 / self.alpha))

    @property
    def mean(self):
        m = self.alpha * self.scale / (self.alpha - 1)
        return wrap(jnp.where(self.alpha > 1, m, jnp.inf))

    @property
    def variance(self):
        a = self.alpha
        v = self.scale ** 2 * a / ((a - 1) ** 2 * (a - 2))
        return wrap(jnp.where(a > 2, v, jnp.inf))

    def entropy(self):
        return wrap(jnp.log(self.scale / self.alpha) + 1 + 1 / self.alpha)
