"""Parameter/support constraints for distributions.

Reference surface: python/mxnet/gluon/probability/distributions/constraint.py
(Constraint.check raising on violation, interval/integer/simplex/cholesky
variants). TPU note: `check` runs eagerly via a host sync — it is a
validation aid, not a jit-path citizen; under tracing it becomes a no-op
pass-through, matching how validate_args is meant for debugging.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .utils import as_jax

__all__ = [
    "Constraint", "Real", "Boolean", "Interval", "OpenInterval",
    "HalfOpenInterval", "IntegerInterval", "IntegerGreaterThan",
    "IntegerGreaterThanEq", "GreaterThan", "GreaterThanEq", "LessThan",
    "LessThanEq", "Positive", "NonNegative", "PositiveInteger",
    "NonNegativeInteger", "UnitInterval", "Simplex", "LowerCholesky",
    "PositiveDefinite", "dependent", "is_dependent",
]


def _eager(x):
    """True when x is a concrete (non-traced) value we can validate."""
    return not isinstance(x, jax.core.Tracer)


class Constraint:
    """Base class: `check(value)` returns value, raises ValueError on violation."""

    def _cond(self, value):  # noqa: ARG002
        raise NotImplementedError

    def check(self, value):
        data = jnp.asarray(as_jax(value))
        if _eager(data):
            ok = bool(jnp.all(self._cond(data)))
            if not ok:
                raise ValueError(
                    f"Constraint violated: expected {type(self).__name__}")
        return value

    def __repr__(self):
        return type(self).__name__


class _Dependent(Constraint):
    """Placeholder for constraints depending on other parameters
    (e.g. Uniform.low < value < Uniform.high)."""

    def check(self, value):
        raise ValueError("Cannot determine validity of dependent constraint")


dependent = _Dependent()


def is_dependent(constraint):
    return isinstance(constraint, _Dependent)


class Real(Constraint):
    def _cond(self, v):
        return v == v  # not NaN


class Boolean(Constraint):
    def _cond(self, v):
        return (v == 0) | (v == 1)


class Interval(Constraint):
    def __init__(self, lower, upper):
        self.lower = lower
        self.upper = upper

    def _cond(self, v):
        return (v >= self.lower) & (v <= self.upper)


class OpenInterval(Interval):
    def _cond(self, v):
        return (v > self.lower) & (v < self.upper)


class HalfOpenInterval(Interval):
    def _cond(self, v):
        return (v >= self.lower) & (v < self.upper)


class UnitInterval(Interval):
    def __init__(self):
        super().__init__(0.0, 1.0)


class GreaterThan(Constraint):
    def __init__(self, lower):
        self.lower = lower

    def _cond(self, v):
        return v > self.lower


class GreaterThanEq(GreaterThan):
    def _cond(self, v):
        return v >= self.lower


class LessThan(Constraint):
    def __init__(self, upper):
        self.upper = upper

    def _cond(self, v):
        return v < self.upper


class LessThanEq(LessThan):
    def _cond(self, v):
        return v <= self.upper


class Positive(GreaterThan):
    def __init__(self):
        super().__init__(0.0)


class NonNegative(GreaterThanEq):
    def __init__(self):
        super().__init__(0.0)


class _IntegerMixin:
    def _int_cond(self, v):
        return v == jnp.floor(v)


class IntegerInterval(Interval, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class IntegerGreaterThan(GreaterThan, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class IntegerGreaterThanEq(GreaterThanEq, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class PositiveInteger(IntegerGreaterThan):
    def __init__(self):
        super().__init__(0)


class NonNegativeInteger(IntegerGreaterThanEq):
    def __init__(self):
        super().__init__(0)


class Simplex(Constraint):
    def _cond(self, v):
        return jnp.all(v >= 0, axis=-1) & (
            jnp.abs(jnp.sum(v, axis=-1) - 1.0) < 1e-6)


class LowerCholesky(Constraint):
    def _cond(self, v):
        tril = jnp.all(jnp.triu(v, k=1) == 0, axis=(-2, -1))
        pos_diag = jnp.all(jnp.diagonal(v, axis1=-2, axis2=-1) > 0, axis=-1)
        return tril & pos_diag


class PositiveDefinite(Constraint):
    def _cond(self, v):
        sym = jnp.all(jnp.abs(v - jnp.swapaxes(v, -1, -2)) < 1e-6,
                      axis=(-2, -1))
        pos = jnp.linalg.eigvalsh(v)[..., 0] > 0
        return sym & pos
