"""Parameter/support constraints for distributions.

Reference surface: python/mxnet/gluon/probability/distributions/constraint.py
(Constraint.check raising on violation, interval/integer/simplex/cholesky
variants). TPU note: `check` runs eagerly via a host sync — it is a
validation aid, not a jit-path citizen; under tracing it becomes a no-op
pass-through, matching how validate_args is meant for debugging.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .utils import as_jax, wrap

__all__ = [
    "Constraint", "Real", "Boolean", "Interval", "OpenInterval",
    "HalfOpenInterval", "IntegerInterval", "IntegerGreaterThan",
    "IntegerGreaterThanEq", "GreaterThan", "GreaterThanEq", "LessThan",
    "LessThanEq", "Positive", "NonNegative", "PositiveInteger",
    "NonNegativeInteger", "UnitInterval", "Simplex", "LowerCholesky",
    "PositiveDefinite", "dependent", "is_dependent",
]


def _eager(x):
    """True when x is a concrete (non-traced) value we can validate."""
    return not isinstance(x, jax.core.Tracer)


class Constraint:
    """Base class: `check(value)` returns value, raises ValueError on violation."""

    def _cond(self, value):  # noqa: ARG002
        raise NotImplementedError

    def check(self, value):
        data = jnp.asarray(as_jax(value))
        if _eager(data):
            ok = bool(jnp.all(self._cond(data)))
            if not ok:
                raise ValueError(
                    f"Constraint violated: expected {type(self).__name__}")
        return value

    def __repr__(self):
        return type(self).__name__


class _Dependent(Constraint):
    """Placeholder for constraints depending on other parameters
    (e.g. Uniform.low < value < Uniform.high)."""

    def check(self, value):
        raise ValueError("Cannot determine validity of dependent constraint")


dependent = _Dependent()


def is_dependent(constraint):
    return isinstance(constraint, _Dependent)


class Real(Constraint):
    def _cond(self, v):
        return v == v  # not NaN


class Boolean(Constraint):
    def _cond(self, v):
        return (v == 0) | (v == 1)


class Interval(Constraint):
    def __init__(self, lower, upper):
        self.lower = lower
        self.upper = upper

    def _cond(self, v):
        return (v >= self.lower) & (v <= self.upper)


class OpenInterval(Interval):
    def _cond(self, v):
        return (v > self.lower) & (v < self.upper)


class HalfOpenInterval(Interval):
    def _cond(self, v):
        return (v >= self.lower) & (v < self.upper)


class UnitInterval(Interval):
    def __init__(self):
        super().__init__(0.0, 1.0)


class GreaterThan(Constraint):
    def __init__(self, lower):
        self.lower = lower

    def _cond(self, v):
        return v > self.lower


class GreaterThanEq(GreaterThan):
    def _cond(self, v):
        return v >= self.lower


class LessThan(Constraint):
    def __init__(self, upper):
        self.upper = upper

    def _cond(self, v):
        return v < self.upper


class LessThanEq(LessThan):
    def _cond(self, v):
        return v <= self.upper


class Positive(GreaterThan):
    def __init__(self):
        super().__init__(0.0)


class NonNegative(GreaterThanEq):
    def __init__(self):
        super().__init__(0.0)


class _IntegerMixin:
    def _int_cond(self, v):
        return v == jnp.floor(v)


class IntegerInterval(Interval, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class IntegerGreaterThan(GreaterThan, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class IntegerGreaterThanEq(GreaterThanEq, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class PositiveInteger(IntegerGreaterThan):
    def __init__(self):
        super().__init__(0)


class NonNegativeInteger(IntegerGreaterThanEq):
    def __init__(self):
        super().__init__(0)


class Simplex(Constraint):
    def _cond(self, v):
        return jnp.all(v >= 0, axis=-1) & (
            jnp.abs(jnp.sum(v, axis=-1) - 1.0) < 1e-6)


class LowerCholesky(Constraint):
    def _cond(self, v):
        tril = jnp.all(jnp.triu(v, k=1) == 0, axis=(-2, -1))
        pos_diag = jnp.all(jnp.diagonal(v, axis1=-2, axis2=-1) > 0, axis=-1)
        return tril & pos_diag


class PositiveDefinite(Constraint):
    def _cond(self, v):
        sym = jnp.all(jnp.abs(v - jnp.swapaxes(v, -1, -2)) < 1e-6,
                      axis=(-2, -1))
        pos = jnp.linalg.eigvalsh(v)[..., 0] > 0
        return sym & pos


class IntegerOpenInterval(OpenInterval, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class IntegerHalfOpenInterval(HalfOpenInterval, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class IntegerLessThan(LessThan, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class IntegerLessThanEq(LessThanEq, _IntegerMixin):
    def _cond(self, v):
        return super()._cond(v) & self._int_cond(v)


class LowerTriangular(Constraint):
    """Square lower-triangular matrices (reference: constraint.py:426)."""

    def _cond(self, v):
        return jnp.all(jnp.tril(v) == v, axis=(-2, -1))


class Cat(Constraint):
    """Apply a sequence of constraints to consecutive slices along
    `axis`, concatenate-style (reference: constraint.py:470)."""

    def __init__(self, constraint_seq, axis=0, lengths=None):
        if not all(isinstance(c, Constraint) for c in constraint_seq):
            raise TypeError("constraint_seq must contain Constraints")
        self._seq = list(constraint_seq)
        self._lengths = list(lengths) if lengths is not None \
            else [1] * len(self._seq)
        if len(self._lengths) != len(self._seq):
            raise ValueError(
                f"number of lengths {len(self._lengths)} != number of "
                f"constraints {len(self._seq)}")
        self._axis = axis

    def check(self, value):
        data = jnp.asarray(as_jax(value))
        total = sum(self._lengths)
        if data.shape[self._axis] != total:
            raise ValueError(
                f"Cat lengths sum to {total} but axis {self._axis} has "
                f"size {data.shape[self._axis]}")
        start = 0
        pieces = []
        for c, length in zip(self._seq, self._lengths):
            sl = jnp.take(data, jnp.arange(start, start + length),
                          axis=self._axis)
            pieces.append(jnp.asarray(as_jax(c.check(sl))))
            start += length
        return wrap(jnp.concatenate(pieces, self._axis))


class Stack(Constraint):
    """Apply one constraint per index along `axis`, stack-style
    (reference: constraint.py:501; imperative mode only there too)."""

    def __init__(self, constraint_seq, axis=0):
        if not all(isinstance(c, Constraint) for c in constraint_seq):
            raise TypeError("constraint_seq must contain Constraints")
        self._seq = list(constraint_seq)
        self._axis = axis

    def check(self, value):
        data = jnp.asarray(as_jax(value))
        if data.shape[self._axis] != len(self._seq):
            raise ValueError(
                f"Stack has {len(self._seq)} constraints but axis "
                f"{self._axis} has size {data.shape[self._axis]}")
        parts = jnp.split(data, data.shape[self._axis], axis=self._axis)
        checked = [
            jnp.asarray(as_jax(c.check(jnp.squeeze(p, self._axis))))
            for p, c in zip(parts, self._seq)]
        return wrap(jnp.stack(checked, self._axis))


__all__ += ["IntegerOpenInterval", "IntegerHalfOpenInterval",
            "IntegerLessThan", "IntegerLessThanEq", "LowerTriangular",
            "Cat", "Stack"]
