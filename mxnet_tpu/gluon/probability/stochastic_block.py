"""StochasticBlock / StochasticSequential.

Reference surface: python/mxnet/gluon/probability/block/
stochastic_block.py — HybridBlocks that accumulate auxiliary losses
(e.g. KL terms in a VAE) during forward via the `collectLoss` decorator
and expose them through `.losses`.
"""
from __future__ import annotations

from functools import wraps

from ..block import HybridBlock

__all__ = ["StochasticBlock", "StochasticSequential"]


class StochasticBlock(HybridBlock):
    """HybridBlock whose forward can stash loss tensors with
    `self.add_loss(...)`; forward must be decorated with
    `@StochasticBlock.collectLoss`."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._losses = []
        self._losscache = []
        self._flag = False

    def add_loss(self, loss):
        self._losscache.append(loss)

    @staticmethod
    def collectLoss(func):  # noqa: N802 - reference API name
        @wraps(func)
        def inner(self, *args, **kwargs):
            func_out = func(self, *args, **kwargs)
            collected_loss = self._losscache
            self._losscache = []
            self._flag = True
            return (func_out, collected_loss)

        return inner

    def __call__(self, *args, **kwargs):
        self._flag = False
        out = super().__call__(*args, **kwargs)
        if not self._flag:
            # Under hybridize() a jit cache hit skips the Python forward,
            # so the decorator flag is not set; the compiled program still
            # returns the (output, losses) structure recorded at trace
            # time, which is the real contract to check. Eager calls always
            # run the decorator, so an unset flag there means it's missing.
            structured = (getattr(self, "_active", False)
                          and isinstance(out, (tuple, list)) and len(out) == 2
                          and isinstance(out[1], (list, tuple)))
            if not structured:
                raise ValueError(
                    "The forward function should be decorated by "
                    "StochasticBlock.collectLoss")
        self._losses = list(out[1])
        return out[0]

    @property
    def losses(self):
        return self._losses


class StochasticSequential(StochasticBlock):
    """Sequential stack of blocks whose losses are concatenated."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._layers = []

    def add(self, *blocks):
        for block in blocks:
            self._layers.append(block)
            self.register_child(block)

    @StochasticBlock.collectLoss
    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = []
            if isinstance(x, (tuple, list)):
                args = x[1:]
                x = x[0]
        if args:
            x = tuple([x] + list(args))
        for block in self._layers:
            if hasattr(block, "_losses"):
                self.add_loss(block._losses)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)
