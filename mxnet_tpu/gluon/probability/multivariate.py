"""Multivariate distributions: Dirichlet, MultivariateNormal.

Reference surface: distributions/dirichlet.py and
multivariate_normal.py (loc + exactly one of cov/precision/scale_tril).
TPU note: MVN math runs through Cholesky + triangular solve
(jax.scipy.linalg), which XLA lowers to the MXU-friendly blocked kernels.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import linalg as jla
from jax.scipy import special as jsp

from . import constraint as C
from .distribution import Distribution
from .utils import as_jax, wrap

__all__ = ["Dirichlet", "MultivariateNormal"]


class Dirichlet(Distribution):
    has_grad = True
    support = C.Simplex()
    arg_constraints = {"alpha": C.Positive()}

    def __init__(self, alpha, validate_args=None):
        self.alpha = jnp.asarray(as_jax(alpha), jnp.float32)
        super().__init__(event_dim=1, validate_args=validate_args)

    def _batch_shape(self):
        return self.alpha.shape[:-1]

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape) + (self.alpha.shape[-1],)
        return Dirichlet(jnp.broadcast_to(self.alpha, b))

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        a = self.alpha
        return wrap(jnp.sum(jsp.xlogy(a - 1, v), axis=-1)
                    + jsp.gammaln(jnp.sum(a, axis=-1))
                    - jnp.sum(jsp.gammaln(a), axis=-1))

    def sample(self, size=None):
        size = self._size(size)
        shape = (self._batch_shape() if size is None else size)
        return wrap(jax.random.dirichlet(self._key(), self.alpha,
                                         shape))

    def sample_n(self, size):
        n = self._size(size) or ()
        return self.sample(tuple(n) + self._batch_shape())

    @property
    def mean(self):
        return wrap(self.alpha / jnp.sum(self.alpha, axis=-1,
                                         keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.alpha, axis=-1, keepdims=True)
        m = self.alpha / a0
        return wrap(m * (1 - m) / (a0 + 1))

    def entropy(self):
        a = self.alpha
        k = a.shape[-1]
        a0 = jnp.sum(a, axis=-1)
        return wrap(jnp.sum(jsp.gammaln(a), axis=-1) - jsp.gammaln(a0)
                    + (a0 - k) * jsp.digamma(a0)
                    - jnp.sum((a - 1) * jsp.digamma(a), axis=-1))


class MultivariateNormal(Distribution):
    r"""MVN parameterized by loc and exactly one of cov / precision /
    scale_tril (reference: multivariate_normal.py)."""

    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real(), "cov": C.PositiveDefinite(),
                       "precision": C.PositiveDefinite(),
                       "scale_tril": C.LowerCholesky()}

    def __init__(self, loc, cov=None, precision=None, scale_tril=None,
                 validate_args=None):
        given = sum(p is not None for p in (cov, precision, scale_tril))
        if given != 1:
            raise ValueError("Exactly one of cov, precision, or scale_tril "
                             "must be specified.")
        self.loc = jnp.asarray(as_jax(loc), jnp.float32)
        if cov is not None:
            self.cov = jnp.asarray(as_jax(cov), jnp.float32)
            self.scale_tril = jnp.linalg.cholesky(self.cov)
        elif precision is not None:
            self.precision = jnp.asarray(as_jax(precision), jnp.float32)
            self.cov = jnp.linalg.inv(self.precision)
            self.scale_tril = jnp.linalg.cholesky(self.cov)
        else:
            self.scale_tril = jnp.asarray(as_jax(scale_tril), jnp.float32)
            self.cov = self.scale_tril @ jnp.swapaxes(self.scale_tril,
                                                      -1, -2)
        super().__init__(event_dim=1, validate_args=validate_args)

    def _batch_shape(self):
        return jnp.broadcast_shapes(self.loc.shape[:-1],
                                    self.scale_tril.shape[:-2])

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape)
        d = self.loc.shape[-1]
        return MultivariateNormal(
            jnp.broadcast_to(self.loc, b + (d,)),
            scale_tril=jnp.broadcast_to(self.scale_tril, b + (d, d)))

    def _half_log_det(self):
        return jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2,
                                            axis2=-1)), axis=-1)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        d = self.loc.shape[-1]
        diff = v - self.loc
        # solve L z = diff  →  z = L^{-1} diff; Mahalanobis = |z|^2
        bshape = jnp.broadcast_shapes(diff.shape[:-1],
                                      self.scale_tril.shape[:-2])
        diff_b = jnp.broadcast_to(diff, bshape + (d,))
        tril_b = jnp.broadcast_to(self.scale_tril, bshape + (d, d))
        z = jla.solve_triangular(tril_b, diff_b[..., None], lower=True)
        maha = jnp.sum(z[..., 0] ** 2, axis=-1)
        return wrap(-0.5 * (d * math.log(2 * math.pi) + maha)
                    - self._half_log_det())

    def sample(self, size=None):
        size = self._size(size)
        bshape = self._batch_shape() if size is None else size
        d = self.loc.shape[-1]
        eps = jax.random.normal(self._key(), tuple(bshape) + (d,))
        return wrap(self.loc + jnp.einsum("...ij,...j->...i",
                                          self.scale_tril, eps))

    def sample_n(self, size):
        n = self._size(size) or ()
        return self.sample(tuple(n) + self._batch_shape())

    @property
    def mean(self):
        return wrap(self.loc)

    @property
    def variance(self):
        return wrap(jnp.diagonal(self.cov, axis1=-2, axis2=-1))

    def entropy(self):
        d = self.loc.shape[-1]
        return wrap(0.5 * d * (1 + math.log(2 * math.pi))
                    + self._half_log_det())
