"""KL divergence registry.

Reference surface: distributions/divergence.py — `kl_divergence(p, q)`
dispatching on (type(p), type(q)) with MRO fallback, `register_kl`
decorator for user pairs, `empirical_kl` Monte-Carlo fallback.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.scipy import special as jsp

from .continuous import (Beta, Cauchy, Exponential, Gamma, Gumbel,
                         HalfNormal, Laplace, Normal, Pareto, Uniform)
from .discrete import (Bernoulli, Binomial, Categorical, Geometric,
                       Multinomial, NegativeBinomial, OneHotCategorical,
                       Poisson)
from .multivariate import Dirichlet, MultivariateNormal
from .utils import as_jax, wrap

__all__ = ["register_kl", "kl_divergence", "empirical_kl"]

_KL_REGISTRY = {}


def register_kl(typeP, typeQ):
    """Decorator registering a KL(p||q) implementation for a type pair."""

    def decorator(func):
        _KL_REGISTRY[(typeP, typeQ)] = func
        return func

    return decorator


def _dispatch_kl(type_p, type_q):
    matches = [(p, q) for (p, q) in _KL_REGISTRY
               if issubclass(type_p, p) and issubclass(type_q, q)]
    if not matches:
        raise NotImplementedError(
            f"KL divergence between {type_p.__name__} and "
            f"{type_q.__name__} is not implemented; consider empirical_kl.")
    # most-derived match first
    matches.sort(key=lambda pq: (len(type_p.__mro__)
                                 - type_p.__mro__.index(pq[0]),
                                 len(type_q.__mro__)
                                 - type_q.__mro__.index(pq[1])),
                 reverse=True)
    return _KL_REGISTRY[matches[0]]


def kl_divergence(p, q):
    r"""KL(p || q) = E_p[log p(x) - log q(x)], closed form via registry."""
    func = _dispatch_kl(type(p), type(q))
    return func(p, q)


def empirical_kl(p, q, n_samples=1):
    """Monte-Carlo estimate of KL(p||q) from n_samples draws of p."""
    samples = p.sample_n((n_samples,))
    lp = as_jax(p.log_prob(samples))
    lq = as_jax(q.log_prob(samples))
    return wrap(jnp.mean(lp - lq, axis=0))


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    pp = jnp.clip(p.prob, 1e-7, 1 - 1e-7)
    qp = jnp.clip(q.prob, 1e-7, 1 - 1e-7)
    return wrap(pp * (jnp.log(pp) - jnp.log(qp))
                + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    lp = p._normalized_logit
    lq = q._normalized_logit
    return wrap(jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1))


@register_kl(OneHotCategorical, OneHotCategorical)
def _kl_onehot_onehot(p, q):
    return _kl_categorical_categorical(p, q)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    result = jnp.log((q.high - q.low) / (p.high - p.low))
    outside = (q.low > p.low) | (q.high < p.high)
    return wrap(jnp.where(outside, jnp.inf, result))


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    # rate = 1/scale
    ratio = q.scale / p.scale  # rate_p / rate_q
    return wrap(jnp.log(ratio) + 1.0 / ratio - 1)


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    return wrap(p.rate * (jnp.log(p.rate) - jnp.log(q.rate))
                - p.rate + q.rate)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    # shape/scale parameterization
    a_p, b_p = p.shape, 1.0 / p.scale
    a_q, b_q = q.shape, 1.0 / q.scale
    t1 = a_q * (jnp.log(b_p) - jnp.log(b_q))
    t2 = jsp.gammaln(a_q) - jsp.gammaln(a_p)
    t3 = (a_p - a_q) * jsp.digamma(a_p)
    t4 = (b_q - b_p) * (a_p / b_p)
    return wrap(t1 + t2 + t3 + t4)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    sum_p = p.alpha + p.beta
    t1 = jsp.betaln(q.alpha, q.beta) - jsp.betaln(p.alpha, p.beta)
    t2 = (p.alpha - q.alpha) * jsp.digamma(p.alpha)
    t3 = (p.beta - q.beta) * jsp.digamma(p.beta)
    t4 = (q.alpha - p.alpha + q.beta - p.beta) * jsp.digamma(sum_p)
    return wrap(t1 + t2 + t3 + t4)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    a_p, a_q = p.alpha, q.alpha
    sum_p = jnp.sum(a_p, axis=-1)
    t1 = jsp.gammaln(sum_p) - jnp.sum(jsp.gammaln(a_p), axis=-1)
    t2 = (jnp.sum(jsp.gammaln(a_q), axis=-1)
          - jsp.gammaln(jnp.sum(a_q, axis=-1)))
    t3 = jnp.sum((a_p - a_q) * (jsp.digamma(a_p)
                                - jsp.digamma(sum_p)[..., None]), axis=-1)
    return wrap(t1 + t2 + t3)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    scale_ratio = p.scale / q.scale
    loc_abs_diff = jnp.abs(p.loc - q.loc)
    t1 = -jnp.log(scale_ratio)
    t2 = loc_abs_diff / q.scale
    t3 = scale_ratio * jnp.exp(-loc_abs_diff / p.scale)
    return wrap(t1 + t2 + t3 - 1)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    from .continuous import _EULER
    ratio = p.scale / q.scale
    t1 = jnp.log(q.scale / p.scale)
    t2 = _EULER * (ratio - 1)
    t3 = jnp.exp((q.loc - p.loc) / q.scale
                 + jsp.gammaln(1 + ratio)) - 1
    t4 = (p.loc - q.loc) / q.scale
    return wrap(t1 + t2 + t3 + t4)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    pp = jnp.clip(p.prob, 1e-7, 1 - 1e-7)
    qp = jnp.clip(q.prob, 1e-7, 1 - 1e-7)
    return wrap(-as_jax(p.entropy()) - jnp.log(qp)
                - (1 - pp) / pp * jnp.log1p(-qp))


@register_kl(Pareto, Pareto)
def _kl_pareto_pareto(p, q):
    scale_ratio = p.scale / q.scale
    alpha_ratio = q.alpha / p.alpha
    t1 = q.alpha * jnp.log(scale_ratio)
    t2 = -jnp.log(alpha_ratio)
    result = t1 + t2 + alpha_ratio - 1
    return wrap(jnp.where(p.scale >= q.scale, result, jnp.inf))


@register_kl(HalfNormal, HalfNormal)
def _kl_halfnormal_halfnormal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    return wrap(0.5 * (var_ratio - 1 - jnp.log(var_ratio)))


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019)
    num = (p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2
    den = 4 * p.scale * q.scale
    return wrap(jnp.log(num / den))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    d = p.loc.shape[-1]
    half_ld_p = p._half_log_det()
    half_ld_q = q._half_log_det()
    q_cov_inv = jnp.linalg.inv(q.cov)
    trace_term = jnp.trace(q_cov_inv @ p.cov, axis1=-2, axis2=-1)
    diff = q.loc - p.loc
    maha = jnp.einsum("...i,...ij,...j->...", diff, q_cov_inv, diff)
    return wrap(half_ld_q - half_ld_p
                + 0.5 * (trace_term + maha - d))


@register_kl(Binomial, Binomial)
def _kl_binomial_binomial(p, q):
    if p.n != q.n:
        raise ValueError("KL between Binomials requires equal n")
    pp = jnp.clip(p.prob, 1e-7, 1 - 1e-7)
    qp = jnp.clip(q.prob, 1e-7, 1 - 1e-7)
    return wrap(p.n * (pp * (jnp.log(pp) - jnp.log(qp))
                       + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp))))


@register_kl(Multinomial, Multinomial)
def _kl_multinomial_multinomial(p, q):
    if p.total_count != q.total_count:
        raise ValueError("KL between Multinomials requires equal "
                         "total_count")
    kl_cat = as_jax(_kl_categorical_categorical(p._categorical,
                                                q._categorical))
    return wrap(p.total_count * kl_cat)


@register_kl(NegativeBinomial, NegativeBinomial)
def _kl_negbin_negbin(p, q):
    return empirical_kl(p, q, n_samples=32)
