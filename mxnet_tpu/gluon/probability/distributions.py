"""`mx.gluon.probability.distributions` (reference path:
gluon/probability/distributions/ — one file per distribution). This
package keeps distributions in family modules (continuous/discrete/
multivariate/transformed); this module re-exports them under the
reference's subpackage spelling."""
from .continuous import *  # noqa: F401,F403
from .discrete import *  # noqa: F401,F403
from .distribution import Distribution, ExponentialFamily  # noqa: F401
from .divergence import kl_divergence, register_kl  # noqa: F401
from .multivariate import *  # noqa: F401,F403
from .transformed_distribution import TransformedDistribution  # noqa: F401
