"""Discrete distributions (plus continuous relaxations).

Reference surface: distributions/{bernoulli,binomial,geometric,poisson,
negative_binomial,categorical,one_hot_categorical,multinomial,
relaxed_bernoulli,relaxed_one_hot_categorical}.py. Dual prob/logit
parameterization preserved (exactly one must be given, as in e.g.
bernoulli.py / categorical.py:47).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from . import constraint as C
from .distribution import Distribution, ExponentialFamily
from .utils import as_jax, cached_property, prob2logit, wrap

__all__ = ["Bernoulli", "Binomial", "Geometric", "Poisson",
           "NegativeBinomial", "Categorical", "OneHotCategorical",
           "Multinomial", "RelaxedBernoulli", "RelaxedOneHotCategorical"]


class _ProbLogit(Distribution):
    """Base handling the exactly-one-of(prob, logit) contract; the missing
    parameterization is derived lazily (reference: utils.prob2logit)."""

    _binary = True

    def __init__(self, prob=None, logit=None, validate_args=None,
                 event_dim=0):
        if (prob is None) == (logit is None):
            raise ValueError(
                "Either `prob` or `logit` must be specified, but not both.")
        if prob is not None:
            self.prob = jnp.asarray(as_jax(prob), jnp.float32)
        else:
            self.logit = jnp.asarray(as_jax(logit), jnp.float32)
        super().__init__(event_dim=event_dim, validate_args=validate_args)

    @cached_property
    def prob(self):
        if self._binary:
            return jax.nn.sigmoid(self.logit)
        return jax.nn.softmax(self.logit, axis=-1)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, self._binary)

    def _param_broadcast(self, batch_shape, cls, **extra):
        new = self.__new__(cls)
        if "prob" in self.__dict__:
            new.prob = jnp.broadcast_to(self.prob, batch_shape)
        else:
            new.logit = jnp.broadcast_to(self.logit, batch_shape)
        for k, v in extra.items():
            setattr(new, k, v)
        new.event_dim = self.event_dim
        new._validate_args = self._validate_args
        return new


class Bernoulli(_ProbLogit, ExponentialFamily):
    support = C.Boolean()
    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}
    has_enumerate_support = True

    def _batch_shape(self):
        return (self.prob if "prob" in self.__dict__ else self.logit).shape

    def broadcast_to(self, batch_shape):
        return self._param_broadcast(tuple(batch_shape), Bernoulli)

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        v = jnp.asarray(as_jax(value))
        # numerically stable BCE on logits
        l = self.logit
        return wrap(v * l - jnp.logaddexp(0.0, l))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        return wrap(jax.random.bernoulli(
            self._key(), self.prob, shape).astype(jnp.float32))

    def sample_n(self, size):
        n = self._size(size) or ()
        return self.sample(tuple(n) + self._batch_shape())

    @property
    def mean(self):
        return wrap(self.prob)

    @property
    def variance(self):
        return wrap(self.prob * (1 - self.prob))

    def entropy(self):
        l = self.logit
        return wrap(jnp.logaddexp(0.0, l) - self.prob * l)

    def enumerate_support(self):
        shape = (2,) + self._batch_shape()
        vals = jnp.zeros(shape).at[1].set(1.0)
        return wrap(vals)

    @property
    def _natural_params(self):
        return (self.logit,)

    def _log_normalizer(self, x):
        return jnp.logaddexp(0.0, x)

    def _mean_carrier_measure(self):
        return 0.0


class Geometric(_ProbLogit):
    r"""Number of failures before first success; support {0, 1, 2, ...}."""

    support = C.NonNegativeInteger()
    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}

    def _batch_shape(self):
        return (self.prob if "prob" in self.__dict__ else self.logit).shape

    def broadcast_to(self, batch_shape):
        return self._param_broadcast(tuple(batch_shape), Geometric)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        p = jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        return wrap(v * jnp.log1p(-p) + jnp.log(p))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        u = jax.random.uniform(self._key(), shape, minval=1e-7,
                               maxval=1.0 - 1e-7)
        p = jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        return wrap(jnp.floor(jnp.log(u) / jnp.log1p(-p)))

    @property
    def mean(self):
        return wrap((1 - self.prob) / self.prob)

    @property
    def variance(self):
        return wrap((1 - self.prob) / self.prob ** 2)

    def entropy(self):
        p = jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        return wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)) / p)


class Binomial(_ProbLogit):
    r"""Binomial(n, prob|logit); n is a python int (static under jit)."""

    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}

    def __init__(self, n=1, prob=None, logit=None, validate_args=None):
        self.n = int(n)
        super().__init__(prob, logit, validate_args)

    @property
    def support(self):
        return C.IntegerInterval(0, self.n)

    def _batch_shape(self):
        return (self.prob if "prob" in self.__dict__ else self.logit).shape

    def broadcast_to(self, batch_shape):
        return self._param_broadcast(tuple(batch_shape), Binomial, n=self.n)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        p = jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        log_comb = (jsp.gammaln(self.n + 1.0) - jsp.gammaln(v + 1.0)
                    - jsp.gammaln(self.n - v + 1.0))
        return wrap(log_comb + v * jnp.log(p)
                    + (self.n - v) * jnp.log1p(-p))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        draws = jax.random.bernoulli(
            self._key(), self.prob, (self.n,) + tuple(shape))
        return wrap(jnp.sum(draws.astype(jnp.float32), axis=0))

    def sample_n(self, size):
        n = self._size(size) or ()
        return self.sample(tuple(n) + self._batch_shape())

    @property
    def mean(self):
        return wrap(self.n * self.prob)

    @property
    def variance(self):
        return wrap(self.n * self.prob * (1 - self.prob))


class Poisson(ExponentialFamily):
    support = C.NonNegativeInteger()
    arg_constraints = {"rate": C.Positive()}

    def __init__(self, rate=1.0, validate_args=None):
        self.rate = jnp.asarray(as_jax(rate), jnp.float32)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.rate.shape

    def broadcast_to(self, batch_shape):
        return Poisson(jnp.broadcast_to(self.rate, tuple(batch_shape)))

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(jsp.xlogy(v, self.rate) - self.rate
                    - jsp.gammaln(v + 1))

    def sample(self, size=None):
        size = self._size(size)
        shape = self.rate.shape if size is None else size
        return wrap(jax.random.poisson(self._key(), self.rate,
                                       shape).astype(jnp.float32))

    def sample_n(self, size):
        n = self._size(size) or ()
        return self.sample(tuple(n) + self.rate.shape)

    @property
    def mean(self):
        return wrap(self.rate)

    @property
    def variance(self):
        return wrap(self.rate)

    @property
    def _natural_params(self):
        return (jnp.log(self.rate),)

    def _log_normalizer(self, x):
        return jnp.exp(x)

    def _mean_carrier_measure(self):
        # E[log(x!)] has no closed form; reference also omits Poisson entropy
        raise NotImplementedError


class NegativeBinomial(_ProbLogit):
    r"""NegativeBinomial(n, prob|logit): number of failures until n
    successes, `prob` = success probability
    (reference: negative_binomial.py:51)."""

    support = C.NonNegativeInteger()
    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}

    def __init__(self, n, prob=None, logit=None, validate_args=None):
        self.n = jnp.asarray(as_jax(n), jnp.float32)
        super().__init__(prob, logit, validate_args)

    def _batch_shape(self):
        p = self.prob if "prob" in self.__dict__ else self.logit
        return jnp.broadcast_shapes(self.n.shape, p.shape)

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape)
        return self._param_broadcast(
            b, NegativeBinomial, n=jnp.broadcast_to(self.n, b))

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        p = jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        log_comb = (jsp.gammaln(v + self.n) - jsp.gammaln(v + 1)
                    - jsp.gammaln(self.n))
        return wrap(log_comb + self.n * jnp.log(p) + v * jnp.log1p(-p))

    def sample(self, size=None):
        # gamma-poisson mixture: lam ~ Gamma(n, (1-p)/p); x ~ Poisson(lam)
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        k1, k2 = jax.random.split(self._key())
        p = jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        lam = jax.random.gamma(k1, self.n, shape) * (1 - p) / p
        return wrap(jax.random.poisson(k2, lam).astype(jnp.float32))

    @property
    def mean(self):
        return wrap(self.n * (1 - self.prob) / self.prob)

    @property
    def variance(self):
        return wrap(self.n * (1 - self.prob) / self.prob ** 2)


class Categorical(_ProbLogit):
    r"""Categorical over {0..num_events-1}; prob/logit shaped
    (..., num_events) (reference: categorical.py:47)."""

    _binary = False
    has_enumerate_support = True

    def __init__(self, num_events, prob=None, logit=None,
                 validate_args=None):
        self.num_events = int(num_events)
        super().__init__(prob, logit, validate_args)

    @property
    def support(self):
        return C.IntegerInterval(0, self.num_events - 1)

    def _batch_shape(self):
        p = self.prob if "prob" in self.__dict__ else self.logit
        return p.shape[:-1]

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape) + (self.num_events,)
        return self._param_broadcast(b, Categorical,
                                     num_events=self.num_events)

    @property
    def _normalized_logit(self):
        return self.logit - jsp.logsumexp(self.logit, axis=-1,
                                          keepdims=True)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value)).astype(jnp.int32)
        logp = self._normalized_logit
        v_b = jnp.broadcast_to(v, jnp.broadcast_shapes(
            v.shape, logp.shape[:-1]))
        logp_b = jnp.broadcast_to(logp, v_b.shape + (self.num_events,))
        return wrap(jnp.take_along_axis(
            logp_b, v_b[..., None], axis=-1).squeeze(-1))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        return wrap(jax.random.categorical(
            self._key(), self._normalized_logit,
            shape=shape).astype(jnp.float32))

    def sample_n(self, size):
        n = self._size(size) or ()
        return self.sample(tuple(n) + self._batch_shape())

    @property
    def mean(self):
        raise NotImplementedError  # undefined for categorical labels

    def entropy(self):
        logp = self._normalized_logit
        return wrap(-jnp.sum(jnp.exp(logp) * logp, axis=-1))

    def enumerate_support(self):
        vals = jnp.arange(self.num_events, dtype=jnp.float32)
        shape = (self.num_events,) + tuple(1 for _ in self._batch_shape())
        return wrap(jnp.broadcast_to(
            vals.reshape(shape), (self.num_events,) + self._batch_shape()))


class OneHotCategorical(Categorical):
    r"""Categorical emitting one-hot vectors; event_dim=1."""

    def __init__(self, num_events, prob=None, logit=None,
                 validate_args=None):
        super().__init__(num_events, prob, logit, validate_args)
        self.event_dim = 1

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape) + (self.num_events,)
        return self._param_broadcast(b, OneHotCategorical,
                                     num_events=self.num_events)

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        return wrap(jnp.sum(v * self._normalized_logit, axis=-1))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        idx = jax.random.categorical(self._key(), self._normalized_logit,
                                     shape=shape)
        return wrap(jax.nn.one_hot(idx, self.num_events,
                                   dtype=jnp.float32))

    @property
    def mean(self):
        return wrap(self.prob)

    @property
    def variance(self):
        return wrap(self.prob * (1 - self.prob))

    def enumerate_support(self):
        eye = jnp.eye(self.num_events)
        shape = ((self.num_events,)
                 + tuple(1 for _ in self._batch_shape())
                 + (self.num_events,))
        return wrap(jnp.broadcast_to(
            eye.reshape(shape),
            (self.num_events,) + self._batch_shape()
            + (self.num_events,)))


class Multinomial(Distribution):
    r"""Multinomial(num_events, prob|logit, total_count) — counts over
    categories; sampling sums total_count one-hot draws
    (reference: multinomial.py:48-99)."""

    arg_constraints = {"prob": C.Simplex(), "logit": C.Real()}

    def __init__(self, num_events, prob=None, logit=None, total_count=1,
                 validate_args=None):
        self.total_count = int(total_count)
        self.num_events = int(num_events)
        self._categorical = OneHotCategorical(num_events, prob, logit)
        super().__init__(event_dim=1, validate_args=validate_args)

    @property
    def prob(self):
        return self._categorical.prob

    @property
    def logit(self):
        return self._categorical.logit

    def _batch_shape(self):
        return self._categorical._batch_shape()

    def broadcast_to(self, batch_shape):
        new = self.__new__(Multinomial)
        new.total_count = self.total_count
        new.num_events = self.num_events
        new._categorical = self._categorical.broadcast_to(batch_shape)
        new.event_dim = self.event_dim
        new._validate_args = self._validate_args
        return new

    def log_prob(self, value):
        v = jnp.asarray(as_jax(value))
        logp = self._categorical._normalized_logit
        log_factorial = (jsp.gammaln(jnp.sum(v, axis=-1) + 1)
                         - jnp.sum(jsp.gammaln(v + 1), axis=-1))
        return wrap(log_factorial + jnp.sum(v * logp, axis=-1))

    def sample(self, size=None):
        size = self._size(size)
        base = self._categorical if size is None else \
            self._categorical.broadcast_to(size)
        onehots = base.sample_n((self.total_count,))
        return wrap(jnp.sum(as_jax(onehots), axis=0))

    @property
    def mean(self):
        return wrap(self.total_count * self.prob)

    @property
    def variance(self):
        return wrap(self.total_count * self.prob * (1 - self.prob))


class RelaxedBernoulli(Distribution):
    r"""Concrete/Gumbel-sigmoid relaxation with pathwise gradients."""

    has_grad = True
    support = C.UnitInterval()
    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}

    def __init__(self, T=1.0, prob=None, logit=None, validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError(
                "Either `prob` or `logit` must be specified, but not both.")
        self.T = jnp.asarray(as_jax(T), jnp.float32)
        if prob is not None:
            self.prob = jnp.asarray(as_jax(prob), jnp.float32)
            self.logit = prob2logit(self.prob, binary=True)
        else:
            self.logit = jnp.asarray(as_jax(logit), jnp.float32)
            self.prob = jax.nn.sigmoid(self.logit)
        super().__init__(event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.logit.shape

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape)
        return RelaxedBernoulli(self.T,
                                logit=jnp.broadcast_to(self.logit, b))

    def sample(self, size=None):
        size = self._size(size)
        shape = self._batch_shape() if size is None else size
        l = jax.random.logistic(self._key(), shape)
        return wrap(jax.nn.sigmoid((self.logit + l) / self.T))

    def log_prob(self, value):
        # BinConcrete density (Maddison et al. 2017, eq. C.7):
        # p(v) = T a v^{-T-1} (1-v)^{-T-1} / (a v^{-T} + (1-v)^{-T})^2
        v = jnp.clip(jnp.asarray(as_jax(value)), 1e-6, 1 - 1e-6)
        logit_v = jnp.log(v) - jnp.log1p(-v)
        diff = self.logit - self.T * logit_v
        return wrap(jnp.log(self.T) + self.logit
                    - (self.T + 1) * jnp.log(v)
                    + (self.T - 1) * jnp.log1p(-v)
                    - 2 * jnp.logaddexp(0.0, diff))


class RelaxedOneHotCategorical(Distribution):
    r"""Gumbel-softmax relaxation of OneHotCategorical."""

    has_grad = True
    support = C.Simplex()
    arg_constraints = {"prob": C.Simplex(), "logit": C.Real()}

    def __init__(self, T=1.0, num_events=None, prob=None, logit=None,
                 validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError(
                "Either `prob` or `logit` must be specified, but not both.")
        self.T = jnp.asarray(as_jax(T), jnp.float32)
        if prob is not None:
            self.prob = jnp.asarray(as_jax(prob), jnp.float32)
            self.logit = jnp.log(jnp.clip(self.prob, 1e-30, None))
        else:
            self.logit = jnp.asarray(as_jax(logit), jnp.float32)
            self.prob = jax.nn.softmax(self.logit, axis=-1)
        self.num_events = (int(num_events) if num_events is not None
                           else self.logit.shape[-1])
        super().__init__(event_dim=1, validate_args=validate_args)

    def _batch_shape(self):
        return self.logit.shape[:-1]

    def broadcast_to(self, batch_shape):
        b = tuple(batch_shape) + (self.num_events,)
        return RelaxedOneHotCategorical(
            self.T, self.num_events, logit=jnp.broadcast_to(self.logit, b))

    def sample(self, size=None):
        size = self._size(size)
        shape = (self._batch_shape() if size is None else size) \
            + (self.num_events,)
        g = jax.random.gumbel(self._key(), shape)
        return wrap(jax.nn.softmax((self.logit + g) / self.T, axis=-1))

    def log_prob(self, value):
        # Concrete density on the simplex (Maddison et al. 2017, eq. 10):
        # p(x) = (K-1)! T^{K-1} prod_k(p_k x_k^{-T-1})
        #        / (sum_k p_k x_k^{-T})^K
        v = jnp.clip(jnp.asarray(as_jax(value)), 1e-30, None)
        k = self.num_events
        logp = self.logit - jsp.logsumexp(self.logit, axis=-1,
                                          keepdims=True)
        score = jnp.sum(logp - (self.T + 1) * jnp.log(v), axis=-1) \
            - k * jsp.logsumexp(logp - self.T * jnp.log(v), axis=-1)
        log_norm = (jsp.gammaln(jnp.asarray(float(k)))
                    + (k - 1) * jnp.log(self.T))
        return wrap(score + log_norm)
