"""Distribution base classes.

Reference surface: python/mxnet/gluon/probability/distributions/
distribution.py (Distribution: log_prob/pdf/cdf/icdf/sample/sample_n/
broadcast_to/mean/variance/entropy/perplexity) and exp_family.py
(ExponentialFamily: entropy via Bregman divergence of the log normalizer).

TPU re-design: sampling draws jax PRNG keys from the global stateful RNG
(mxnet_tpu._random), so `d.sample()` is reproducible under mx.seed and
trace-safe inside HybridBlock via the key-provider stack; log_prob math is
pure jax.numpy, fused by XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import _random
from .utils import as_jax, wrap

__all__ = ["Distribution", "ExponentialFamily"]


class Distribution:
    """Base class for probability distributions."""

    has_grad = False
    has_enumerate_support = False
    support = None
    arg_constraints = {}
    _validate_args = False

    @staticmethod
    def set_default_validate_args(value):
        if value not in (True, False):
            raise ValueError("validate_args must be True or False")
        Distribution._validate_args = value

    def __init__(self, event_dim=None, validate_args=None):
        self.event_dim = event_dim
        if validate_args is not None:
            self._validate_args = validate_args
        if self._validate_args:
            from .constraint import is_dependent

            for param, constraint in self.arg_constraints.items():
                if is_dependent(constraint):
                    continue
                if param not in self.__dict__ and isinstance(
                        getattr(type(self), param, None), property):
                    continue
                val = getattr(self, param, None)
                if val is not None:
                    constraint.check(val)

    # -- shape helpers -------------------------------------------------
    def _size(self, size):
        if size is None:
            return None
        if isinstance(size, int):
            return (size,)
        return tuple(size)

    def _key(self):
        return _random.next_key()

    # -- core API ------------------------------------------------------
    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return wrap(jnp.exp(as_jax(self.log_prob(value))))

    pdf = prob

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def sample(self, size=None):
        raise NotImplementedError

    def sample_n(self, size):
        """Draw (n,) + batch_shape samples (reference: sample_n)."""
        n = self._size(size) or ()
        return self.sample(tuple(n) + tuple(self._batch_shape()))

    def _batch_shape(self):
        raise NotImplementedError

    def broadcast_to(self, batch_shape):
        raise NotImplementedError

    def enumerate_support(self):
        raise NotImplementedError

    def _validate_samples(self, value):
        if self.support is not None:
            self.support.check(value)

    # -- moments -------------------------------------------------------
    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return wrap(jnp.sqrt(as_jax(self.variance)))

    def entropy(self):
        raise NotImplementedError

    def perplexity(self):
        return wrap(jnp.exp(as_jax(self.entropy())))

    def __repr__(self):
        args = ", ".join(
            f"{k}" for k in self.arg_constraints if k in self.__dict__)
        return f"{type(self).__name__}({args})"


class ExponentialFamily(Distribution):
    r"""Distributions of form  p(x|θ) = h(x) exp(η(θ)·T(x) − A(η)).

    `entropy()` is computed from the log-normalizer's Bregman divergence:
    H = A(η) − η·∇A(η) + E[−log h(x)] via jax autodiff on _log_normalizer
    (the reference differentiates through its autograd tape the same way).
    """

    @property
    def _natural_params(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    def _mean_carrier_measure(self):
        # E[-log h(x)]; zero for Normal/Exponential etc.
        raise NotImplementedError

    def entropy(self):
        nparams = [jnp.asarray(as_jax(p)) for p in self._natural_params]
        lg_normal = self._log_normalizer(*nparams)
        gradients = jax.grad(
            lambda *ps: jnp.sum(self._log_normalizer(*ps)),
            argnums=tuple(range(len(nparams))))(*nparams)
        result = lg_normal + self._mean_carrier_measure()
        for np_, g in zip(nparams, gradients):
            result = result - np_ * g
        return wrap(result)
