"""Fused multi-layer RNN/LSTM/GRU (reference: gluon/rnn/rnn_layer.py over
the fused src/operator/rnn.cc / cuDNN RNN kernel).

TPU re-design: the time loop is a `lax.scan` (XLA unrolls/pipelines it; the
per-step matmuls hit the MXU batched), layers stacked in python, optional
bidirectional concat. The gate weights use the reference's layout
(i2h (G*H, I), h2h (G*H, H), gate order: LSTM [i,f,g,o], GRU [r,z,n]) so
checkpoints translate directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ndarray.ndarray import NDArray, apply_op
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RNN", "LSTM", "GRU"]


def _rnn_step(mode):
    if mode == "rnn_tanh":
        act = jnp.tanh
    elif mode == "rnn_relu":
        act = jax.nn.relu

    def step_rnn(carry, x_t, wi, wh, bi, bh):
        (h,) = carry
        h_new = act(x_t @ wi.T + bi + h @ wh.T + bh)
        return (h_new,), h_new

    def step_lstm(carry, x_t, wi, wh, bi, bh):
        h, c = carry
        gates = x_t @ wi.T + bi + h @ wh.T + bh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    def step_gru(carry, x_t, wi, wh, bi, bh):
        (h,) = carry
        gi = x_t @ wi.T + bi
        gh = h @ wh.T + bh
        ir, iz, in_ = jnp.split(gi, 3, axis=-1)
        hr, hz, hn = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(in_ + r * hn)
        h_new = (1 - z) * n + z * h
        return (h_new,), h_new

    if mode == "lstm":
        return step_lstm
    if mode == "gru":
        return step_gru
    return step_rnn


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers=1, layout="TNC",
                 dropout=0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, h2r_weight_initializer=None,
                 **kwargs):  # noqa: ARG002
        super().__init__()
        assert layout in ("TNC", "NTC")
        if projection_size and mode != "lstm":
            raise ValueError("projection_size is LSTM-only (LSTMP, "
                             "reference: rnn_layer.py projection_size)")
        self._mode = mode
        self._hidden = hidden_size
        self._layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._proj = projection_size or 0
        self._gates = {"lstm": 4, "gru": 3}.get(mode, 1)
        ng, nh = self._gates, hidden_size
        nr = self._proj or nh          # recurrent (projected) width
        for layer in range(num_layers):
            for d in range(self._dir):
                sfx = f"l{layer}" + ("_r" if d else "")
                in_size = input_size if layer == 0 else nr * self._dir
                self.register_parameter(
                    f"{sfx}_i2h_weight",
                    Parameter(f"{sfx}_i2h_weight", shape=(ng * nh, in_size),
                              init=i2h_weight_initializer,
                              allow_deferred_init=True))
                self.register_parameter(
                    f"{sfx}_h2h_weight",
                    Parameter(f"{sfx}_h2h_weight", shape=(ng * nh, nr),
                              init=h2h_weight_initializer,
                              allow_deferred_init=True))
                if self._proj:
                    # LSTMP recurrent projection (reference:
                    # src/operator/rnn.cc projection_size / cuDNN LSTMP)
                    self.register_parameter(
                        f"{sfx}_h2r_weight",
                        Parameter(f"{sfx}_h2r_weight",
                                  shape=(self._proj, nh),
                                  init=h2r_weight_initializer))
                self.register_parameter(
                    f"{sfx}_i2h_bias",
                    Parameter(f"{sfx}_i2h_bias", shape=(ng * nh,),
                              init=i2h_bias_initializer))
                self.register_parameter(
                    f"{sfx}_h2h_bias",
                    Parameter(f"{sfx}_h2h_bias", shape=(ng * nh,),
                              init=h2h_bias_initializer))

    def _defer(self, in_size):
        ng, nh = self._gates, self._hidden
        for layer in range(self._layers):
            lin = in_size if layer == 0 else (self._proj or nh) * self._dir
            for d in range(self._dir):
                sfx = f"l{layer}" + ("_r" if d else "")
                p = self._reg_params[f"{sfx}_i2h_weight"]
                if p._is_deferred:
                    p._finish_deferred_init((ng * nh, lin))

    def state_info(self, batch_size=0):
        h_shape = (self._layers * self._dir, batch_size,
                   self._proj or self._hidden)
        if self._mode == "lstm":
            c_shape = (self._layers * self._dir, batch_size, self._hidden)
            return [{"shape": h_shape}, {"shape": c_shape}]
        return [{"shape": h_shape}]

    def begin_state(self, batch_size=0, func=None, **kwargs):  # noqa: ARG002
        from ... import numpy as mnp

        return [mnp.zeros(info["shape"])
                for info in self.state_info(batch_size)]

    def forward(self, x, states=None):
        self._defer(x.shape[-1])
        batch_axis = 1 if self._layout == "TNC" else 0
        batch = x.shape[batch_axis]
        return_states = states is not None
        if states is None:
            states = self.begin_state(batch)
        if isinstance(states, NDArray):
            states = [states]
        mode, layers, ndir, hidden = (self._mode, self._layers, self._dir,
                                      self._hidden)
        layout, dropout = self._layout, self._dropout
        step = _rnn_step(mode)
        params = []
        nproj = self._proj
        per = 5 if nproj else 4
        # inter-layer dropout (reference: rnn.cc dropout between stacked
        # layers, train-mode only); keys generated per call so each step
        # draws fresh masks
        from ... import _random
        from ...autograd import is_training

        drop_keys = []
        if dropout and layers > 1 and is_training():
            drop_keys = [_random.next_key() for _ in range(layers - 1)]
        n_params = layers * ndir * per
        for layer in range(layers):
            for d in range(ndir):
                sfx = f"l{layer}" + ("_r" if d else "")
                params.extend([
                    self._reg_params[f"{sfx}_i2h_weight"].data_for(x),
                    self._reg_params[f"{sfx}_h2h_weight"].data_for(x),
                    self._reg_params[f"{sfx}_i2h_bias"].data_for(x),
                    self._reg_params[f"{sfx}_h2h_bias"].data_for(x),
                ])
                if nproj:
                    params.append(
                        self._reg_params[f"{sfx}_h2r_weight"].data_for(x))

        def fused(x_, *flat):
            # flat: states (1 or 2), params, then dropout keys
            n_states = 2 if mode == "lstm" else 1
            st = flat[:n_states]
            ps = flat[n_states:n_states + n_params]
            keys = flat[n_states + n_params:]
            seq = x_ if layout == "TNC" else jnp.swapaxes(x_, 0, 1)
            out_states = []
            inp = seq
            idx = 0
            for layer in range(layers):
                outs = []
                for d in range(ndir):
                    wi, wh, bi, bh = ps[idx : idx + 4]
                    wr = ps[idx + 4] if per == 5 else None
                    idx += per
                    sl = layer * ndir + d
                    carry = tuple(s[sl] for s in st)
                    xs = inp if d == 0 else jnp.flip(inp, 0)

                    if wr is None:
                        def f(c, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                            return step(c, xt, wi, wh, bi, bh)
                    else:
                        # LSTMP: project the hidden state before it
                        # recurs (h carries size P, c stays size H)
                        def f(c, xt, wi=wi, wh=wh, bi=bi, bh=bh, wr=wr):
                            (h_new, c_new), _ = step(c, xt, wi, wh, bi,
                                                     bh)
                            h_p = h_new @ wr.T
                            return (h_p, c_new), h_p

                    final, ys = jax.lax.scan(f, carry, xs)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    outs.append(ys)
                    out_states.append(final)
                inp = outs[0] if ndir == 1 else jnp.concatenate(outs, -1)
                if keys and layer != layers - 1:
                    keep = jax.random.bernoulli(keys[layer], 1.0 - dropout,
                                                inp.shape)
                    inp = jnp.where(keep, inp / (1.0 - dropout), 0.0)
            out = inp if layout == "TNC" else jnp.swapaxes(inp, 0, 1)
            new_states = []
            for si in range(n_states):
                new_states.append(jnp.stack([s[si] for s in out_states]))
            return (out, *new_states)

        result = apply_op(fused, x, *states, *params, *drop_keys,
                          name=f"RNN({mode})")
        out, new_states = result[0], list(result[1:])
        if return_states:
            if mode == "lstm":
                return out, new_states
            return out, new_states[0] if len(new_states) == 1 else new_states
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden}, "
                f"num_layers={self._layers}, bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="tanh",
                 **kwargs):
        super().__init__(f"rnn_{activation}", hidden_size, num_layers,
                         **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("gru", hidden_size, num_layers, **kwargs)
