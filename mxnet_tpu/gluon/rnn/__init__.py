"""Recurrent layers (reference: python/mxnet/gluon/rnn/)."""
from .rnn_cell import (  # noqa: F401
    GRUCell,
    HybridSequentialRNNCell,
    LSTMCell,
    RecurrentCell,
    RNNCell,
    SequentialRNNCell,
)
from .rnn_layer import GRU, LSTM, RNN  # noqa: F401
