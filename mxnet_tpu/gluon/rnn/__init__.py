"""Recurrent layers (reference: python/mxnet/gluon/rnn/)."""
from .rnn_cell import (  # noqa: F401
    BidirectionalCell,
    DropoutCell,
    GRUCell,
    HybridSequentialRNNCell,
    LSTMCell,
    ModifierCell,
    RecurrentCell,
    ResidualCell,
    RNNCell,
    SequentialRNNCell,
    ZoneoutCell,
)
from .rnn_layer import GRU, LSTM, RNN  # noqa: F401
