"""RNN cells for step-wise unrolling (reference: gluon/rnn/rnn_cell.py)."""
from __future__ import annotations

from ... import numpy as mnp
from ... import numpy_extension as npx
from ...ndarray.ndarray import apply_op
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "HybridSequentialRNNCell"]


class RecurrentCell(HybridBlock):
    """Base cell: single-step forward(x_t, states) -> (out, states)."""

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):  # noqa: ARG002
        return [mnp.zeros(info["shape"])
                for info in self.state_info(batch_size)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):  # noqa: ARG002
        """Python unroll over time steps (reference: RecurrentCell.unroll).

        Under hybridize the whole unroll is traced into one XLA program —
        the compiler pipelines the steps (no python overhead at run time).
        """
        axis = 1 if layout == "NTC" else 0
        batch = inputs.shape[0 if layout == "NTC" else 1]
        states = begin_state if begin_state is not None \
            else self.begin_state(batch)
        outputs = []
        for t in range(length):
            x_t = inputs[:, t] if axis == 1 else inputs[t]
            out, states = self(x_t, states)
            outputs.append(out)
        if merge_outputs is False:
            return outputs, states
        stacked = mnp.stack(outputs, axis=axis)
        return stacked, states


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden = hidden_size
        self._act = activation
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(hidden_size, hidden_size),
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden)}]

    def forward(self, x, states):
        if self.i2h_weight._is_deferred:
            self.i2h_weight._finish_deferred_init(
                (self._hidden, x.shape[-1]))
        h = states[0]
        i2h = npx.fully_connected(x, self.i2h_weight.data_for(x),
                                  self.i2h_bias.data_for(x), flatten=False)
        h2h = npx.fully_connected(h, self.h2h_weight.data_for(x),
                                  self.h2h_bias.data_for(x), flatten=False)
        out = npx.activation(i2h + h2h, self._act)
        return out, [out]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden = hidden_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(4 * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(4 * hidden_size, hidden_size),
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden)},
                {"shape": (batch_size, self._hidden)}]

    def forward(self, x, states):
        import jax
        import jax.numpy as jnp

        if self.i2h_weight._is_deferred:
            self.i2h_weight._finish_deferred_init(
                (4 * self._hidden, x.shape[-1]))

        def fn(x_, h, c, wi, wh, bi, bh):
            gates = x_ @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, c_new

        h, c = apply_op(fn, x, states[0], states[1],
                        self.i2h_weight.data_for(x),
                        self.h2h_weight.data_for(x),
                        self.i2h_bias.data_for(x),
                        self.h2h_bias.data_for(x), name="LSTMCell")
        return h, [h, c]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden = hidden_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(3 * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(3 * hidden_size, hidden_size),
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(3 * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(3 * hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden)}]

    def forward(self, x, states):
        import jax
        import jax.numpy as jnp

        if self.i2h_weight._is_deferred:
            self.i2h_weight._finish_deferred_init(
                (3 * self._hidden, x.shape[-1]))

        def fn(x_, h, wi, wh, bi, bh):
            gi = x_ @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn)
            return (1 - z) * n + z * h

        h = apply_op(fn, x, states[0],
                     self.i2h_weight.data_for(x),
                     self.h2h_weight.data_for(x),
                     self.i2h_bias.data_for(x),
                     self.h2h_bias.data_for(x), name="GRUCell")
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference: SequentialRNNCell)."""

    def __init__(self):
        super().__init__()

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        out = []
        for cell in self._children.values():
            out.extend(cell.state_info(batch_size))
        return out

    def __len__(self):
        return len(self._children)

    def forward(self, x, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            x, new = cell(x, states[p : p + n])
            p += n
            next_states.extend(new)
        return x, next_states


HybridSequentialRNNCell = SequentialRNNCell
