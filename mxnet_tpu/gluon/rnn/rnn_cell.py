"""RNN cells for step-wise unrolling (reference: gluon/rnn/rnn_cell.py)."""
from __future__ import annotations

from ... import numpy as mnp
from ... import numpy_extension as npx
from ...ndarray.ndarray import apply_op
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


class RecurrentCell(HybridBlock):
    """Base cell: single-step forward(x_t, states) -> (out, states)."""

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def reset(self):
        """Clear per-sequence step state, recursing into children
        (reference: RecurrentCell.reset). Called at the start of every
        unroll so e.g. ZoneoutCell's previous-output memory never leaks
        across sequences."""
        for child in self._children.values():
            if isinstance(child, RecurrentCell):
                child.reset()

    def begin_state(self, batch_size=0, func=None, **kwargs):  # noqa: ARG002
        return [mnp.zeros(info["shape"])
                for info in self.state_info(batch_size)]

    @staticmethod
    def _format_sequence(length, inputs, layout, merge_outputs):
        """Normalize unroll inputs (reference rnn_cell.py
        _format_sequence): accepts one merged tensor or a list of
        per-step tensors; merge_outputs=None mirrors the input format.
        Returns (merged_tensor, resolved_merge_outputs, batch, axis)."""
        axis = 1 if layout == "NTC" else 0
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != length:
                raise ValueError(
                    f"unroll length {length} != len(inputs) {len(inputs)}")
            if merge_outputs is None:
                merge_outputs = False
            inputs = mnp.stack(list(inputs), axis=axis)
        else:
            if inputs.shape[axis] != length:
                raise ValueError(
                    f"unroll length {length} != inputs time dim "
                    f"{inputs.shape[axis]} (reference _format_sequence "
                    "asserts the same)")
            if merge_outputs is None:
                merge_outputs = True
        batch = inputs.shape[0 if layout == "NTC" else 1]
        return inputs, merge_outputs, batch, axis

    @staticmethod
    def _unmerge(outputs, length, axis):
        return [outputs[:, t] if axis == 1 else outputs[t]
                for t in range(length)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Python unroll over time steps (reference: RecurrentCell.unroll
        + _format_sequence: inputs may be one merged tensor OR a list of
        per-step (N, C) tensors; merge_outputs=None mirrors the input
        format. valid_length masks outputs past each row's length and
        freezes the carried states there (reference uses SequenceMask +
        masked state updates).

        Under hybridize the whole unroll is traced into one XLA program —
        the compiler pipelines the steps (no python overhead at run time).
        """
        self.reset()
        inputs, merge_outputs, batch, axis = self._format_sequence(
            length, inputs, layout, merge_outputs)
        states = begin_state if begin_state is not None \
            else self.begin_state(batch)
        outputs = []
        for t in range(length):
            x_t = inputs[:, t] if axis == 1 else inputs[t]
            out, new_states = self(x_t, states)
            if valid_length is not None:
                alive = (valid_length > t)
                m_out = alive.reshape(
                    (-1,) + (1,) * (out.ndim - 1)).astype(out.dtype)
                out = out * m_out
                frozen = []
                for ns, s in zip(new_states, states):
                    m = alive.reshape(
                        (-1,) + (1,) * (ns.ndim - 1)).astype(ns.dtype)
                    frozen.append(ns * m + s * (1 - m))
                states = frozen
            else:
                states = new_states
            outputs.append(out)
        if not merge_outputs:
            return outputs, states
        return mnp.stack(outputs, axis=axis), states


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden = hidden_size
        self._act = activation
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(hidden_size, hidden_size),
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden)}]

    def forward(self, x, states):
        if self.i2h_weight._is_deferred:
            self.i2h_weight._finish_deferred_init(
                (self._hidden, x.shape[-1]))
        h = states[0]
        i2h = npx.fully_connected(x, self.i2h_weight.data_for(x),
                                  self.i2h_bias.data_for(x), flatten=False)
        h2h = npx.fully_connected(h, self.h2h_weight.data_for(x),
                                  self.h2h_bias.data_for(x), flatten=False)
        out = npx.activation(i2h + h2h, self._act)
        return out, [out]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden = hidden_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(4 * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(4 * hidden_size, hidden_size),
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden)},
                {"shape": (batch_size, self._hidden)}]

    def forward(self, x, states):
        import jax
        import jax.numpy as jnp

        if self.i2h_weight._is_deferred:
            self.i2h_weight._finish_deferred_init(
                (4 * self._hidden, x.shape[-1]))

        def fn(x_, h, c, wi, wh, bi, bh):
            gates = x_ @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, c_new

        h, c = apply_op(fn, x, states[0], states[1],
                        self.i2h_weight.data_for(x),
                        self.h2h_weight.data_for(x),
                        self.i2h_bias.data_for(x),
                        self.h2h_bias.data_for(x), name="LSTMCell")
        return h, [h, c]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden = hidden_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(3 * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(3 * hidden_size, hidden_size),
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(3 * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(3 * hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden)}]

    def forward(self, x, states):
        import jax
        import jax.numpy as jnp

        if self.i2h_weight._is_deferred:
            self.i2h_weight._finish_deferred_init(
                (3 * self._hidden, x.shape[-1]))

        def fn(x_, h, wi, wh, bi, bh):
            gi = x_ @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn)
            return (1 - z) * n + z * h

        h = apply_op(fn, x, states[0],
                     self.i2h_weight.data_for(x),
                     self.h2h_weight.data_for(x),
                     self.i2h_bias.data_for(x),
                     self.h2h_bias.data_for(x), name="GRUCell")
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference: SequentialRNNCell)."""

    def __init__(self):
        super().__init__()

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        out = []
        for cell in self._children.values():
            out.extend(cell.state_info(batch_size))
        return out

    def __len__(self):
        return len(self._children)

    def forward(self, x, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            x, new = cell(x, states[p : p + n])
            p += n
            next_states.extend(new)
        return x, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Cell-by-cell unroll (reference SequentialRNNCell.unroll): each
        child consumes the previous child's full output sequence — so
        un-steppable children (BidirectionalCell) work inside a stack."""
        self.reset()
        inputs, merge_outputs, batch, axis = self._format_sequence(
            length, inputs, layout, merge_outputs)
        states = begin_state if begin_state is not None \
            else self.begin_state(batch)
        p = 0
        next_states = []
        for cell in self._children.values():
            n = len(cell.state_info(batch))
            inputs, new = cell.unroll(
                length, inputs, begin_state=states[p : p + n],
                layout=layout, merge_outputs=True,
                valid_length=valid_length)
            p += n
            next_states.extend(new)
        if not merge_outputs:
            return self._unmerge(inputs, length, axis), next_states
        return inputs, next_states


HybridSequentialRNNCell = SequentialRNNCell


class DropoutCell(RecurrentCell):
    """Applies dropout on input, passes states through (reference:
    rnn_cell.py:838 DropoutCell). Active only under autograd.record."""

    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):  # noqa: ARG002
        return []

    def forward(self, inputs, states):
        if self._rate > 0:
            inputs = npx.dropout(inputs, p=self._rate,
                                 axes=self._axes or None)
        return inputs, states

    def __repr__(self):
        return f"DropoutCell(rate={self._rate}, axes={self._axes})"


class ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (reference: rnn_cell.py:893).

    Parameters belong to the base cell; the modifier only changes the
    step computation."""

    def __init__(self, base_cell):
        super().__init__()
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return self.base_cell.begin_state(batch_size, func, **kwargs)

    def __repr__(self):
        return f"{type(self).__name__}({self.base_cell!r})"


class ZoneoutCell(ModifierCell):
    """Zoneout (Krueger et al. 2016): each step keeps the previous
    output/state elementwise with probability p (reference:
    rnn_cell.py:935). Inactive outside autograd.record — the dropout
    masks collapse to ones and the base cell passes through."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        if isinstance(base_cell, BidirectionalCell):
            raise ValueError(
                "BidirectionalCell doesn't support zoneout — wrap the "
                "cells underneath instead")
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()  # recurse into base_cell (nested zoneouts)
        self._prev_output = None

    def hybridize(self, active=True, **kwargs):
        # The previous-output memory is a Python attribute: caching this
        # cell's OWN stepped program would freeze step-1's zeros branch
        # and silently disable zoneout. Keep the zoneout step eager and
        # let the base cell (a pure step) hybridize underneath.
        self.base_cell.hybridize(active, **kwargs)
        return self

    def forward(self, inputs, states):
        from ... import autograd as ag

        next_output, next_states = self.base_cell(inputs, states)
        p_out, p_st = self.zoneout_outputs, self.zoneout_states
        if not ag.is_training():
            # dropout masks are identity outside training — skip the
            # ones/where work, but still record prev like the reference
            # (a training step may continue this sequence)
            self._prev_output = next_output
            return next_output, next_states

        def mask(p, like):
            # nonzero where the NEW value is taken (reference formula)
            return npx.dropout(mnp.ones(like.shape), p=p)

        prev = self._prev_output
        if prev is None:
            prev = mnp.zeros(next_output.shape)
        output = (mnp.where(mask(p_out, next_output), next_output, prev)
                  if p_out != 0.0 else next_output)
        next_states = ([mnp.where(mask(p_st, ns), ns, os)
                        for ns, os in zip(next_states, states)]
                       if p_st != 0.0 else next_states)
        self._prev_output = output
        return output, next_states


class ResidualCell(ModifierCell):
    """Output = base cell output + input (GNMT residual recipe,
    reference: rnn_cell.py:984)."""

    def forward(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(RecurrentCell):
    """Unroll a forward and a backward cell over the sequence and concat
    their step outputs on the feature axis (reference: rnn_cell.py:1029).
    Cannot be single-stepped — use unroll()."""

    def __init__(self, l_cell, r_cell):
        super().__init__()
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return (self.l_cell.state_info(batch_size)
                + self.r_cell.state_info(batch_size))

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return (self.l_cell.begin_state(batch_size, func, **kwargs)
                + self.r_cell.begin_state(batch_size, func, **kwargs))

    def forward(self, inputs, states):  # noqa: ARG002
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        if valid_length is not None:
            raise NotImplementedError(
                "valid_length is not supported by BidirectionalCell yet")
        self.reset()
        inputs, merge_outputs, batch, axis = self._format_sequence(
            length, inputs, layout, merge_outputs)
        states = begin_state if begin_state is not None \
            else self.begin_state(batch)
        n_l = len(self.l_cell.state_info(batch))
        rev = mnp.flip(inputs, axis=axis)
        l_out, l_states = self.l_cell.unroll(
            length, inputs, begin_state=states[:n_l], layout=layout)
        r_out, r_states = self.r_cell.unroll(
            length, rev, begin_state=states[n_l:], layout=layout)
        out = mnp.concatenate([l_out, mnp.flip(r_out, axis=axis)], axis=-1)
        if not merge_outputs:
            return self._unmerge(out, length, axis), l_states + r_states
        return out, l_states + r_states

    def __repr__(self):
        return (f"BidirectionalCell(forward={self.l_cell!r}, "
                f"backward={self.r_cell!r})")


# reference rnn_cell.py defines HybridRecurrentCell as the hybridizable
# base; here every cell is a HybridBlock already, so they are one class
HybridRecurrentCell = RecurrentCell


class LSTMPCell(RecurrentCell):
    """LSTM with a recurrent projection (reference: rnn_cell.py:1284
    LSTMPCell, arXiv:1402.1128): gates read the PROJECTED recurrence
    r_{t-1} (size P), the cell state keeps full hidden size H, and the
    output is r_t = h_t @ W_hr^T. States: [r (B, P), c (B, H)]."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden = hidden_size
        self._proj = projection_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(4 * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(4 * hidden_size,
                                           projection_size),
                                    init=h2h_weight_initializer)
        self.h2r_weight = Parameter("h2r_weight",
                                    shape=(projection_size, hidden_size),
                                    init=h2r_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._proj)},
                {"shape": (batch_size, self._hidden)}]

    def forward(self, x, states):
        import jax
        import jax.numpy as jnp

        if self.i2h_weight._is_deferred:
            self.i2h_weight._finish_deferred_init(
                (4 * self._hidden, x.shape[-1]))

        def fn(x_, r, c, wi, wh, wr, bi, bh):
            gates = x_ @ wi.T + bi + r @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            r_new = h_new @ wr.T
            return r_new, c_new

        r, c = apply_op(fn, x, states[0], states[1],
                        self.i2h_weight.data_for(x),
                        self.h2h_weight.data_for(x),
                        self.h2r_weight.data_for(x),
                        self.i2h_bias.data_for(x),
                        self.h2h_bias.data_for(x), name="LSTMPCell")
        return r, [r, c]


class VariationalDropoutCell(ModifierCell):
    """Variational dropout (reference: rnn_cell.py:1110,
    arXiv:1512.05287): ONE dropout mask per sequence for each of
    inputs / outputs / first-state, drawn at the first step and reused
    until reset(). Active only while autograd records in train mode."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        if drop_states and isinstance(base_cell, BidirectionalCell):
            raise ValueError(
                "BidirectionalCell doesn't support state dropout "
                "(reference assertion)")
        super().__init__(base_cell)
        self._di, self._ds, self._do = drop_inputs, drop_states, drop_outputs
        self._masks = {}

    def hybridize(self, active=True, **kwargs):
        # the per-sequence masks live in a Python attribute: tracing this
        # cell's own step would leak tracers into self._masks (same
        # guard as ZoneoutCell above). Hybridize only the base cell.
        self.base_cell.hybridize(active, **kwargs)
        return self

    def reset(self):
        super().reset()
        self._masks = {}

    def _mask(self, kind, rate, like):
        from ... import _random
        from ...autograd import is_training

        if not rate or not is_training():
            return None
        m = self._masks.get(kind)
        if m is None or m.shape != like.shape:
            import jax

            key = _random.next_key()
            keep = jax.random.bernoulli(key, 1.0 - rate, like.shape)
            # mask dtype follows the tensor it scales (bf16 under AMP)
            m = (keep / (1.0 - rate)).astype(like.dtype)
            self._masks[kind] = m
        return m

    def forward(self, inputs, states):
        mi = self._mask("i", self._di, inputs)
        if mi is not None:
            inputs = apply_op(lambda x, m: x * m, inputs,
                              _wrap(mi), name="vardrop_in")
        ms = self._mask("s", self._ds, states[0])
        if ms is not None:
            states = [apply_op(lambda s, m: s * m, states[0],
                               _wrap(ms), name="vardrop_state")] \
                + list(states[1:])
        out, new_states = self.base_cell(inputs, states)
        mo = self._mask("o", self._do, out)
        if mo is not None:
            out = apply_op(lambda y, m: y * m, out,
                           _wrap(mo), name="vardrop_out")
        return out, new_states

    def __repr__(self):
        return (f"VariationalDropoutCell({self.base_cell!r}, "
                f"i={self._di}, s={self._ds}, o={self._do})")


def _wrap(jarr):
    from ...ndarray.ndarray import NDArray

    return NDArray(jarr)


__all__ += ["HybridRecurrentCell", "LSTMPCell", "VariationalDropoutCell"]
