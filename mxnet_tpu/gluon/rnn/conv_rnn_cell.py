"""Convolutional RNN cells (reference: gluon/rnn/conv_rnn_cell.py).

ConvRNN/ConvLSTM ("Convolutional LSTM Network", Xingjian et al.,
NIPS 2015)/ConvGRU over 1/2/3 spatial dims: i2h and h2h are
convolutions instead of dense maps, state keeps the spatial grid.
h2h padding is derived (dilate·(k−1)/2, odd kernels only) so the
hidden grid size is step-invariant.
"""
from __future__ import annotations

from ... import numpy as mnp
from ... import numpy_extension as npx
from ..parameter import Parameter
from .rnn_cell import RecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


def _conv_out(sizes, kernel, pad, dilate):
    return tuple((s + 2 * p - d * (k - 1) - 1) + 1
                 for s, k, p, d in zip(sizes, kernel, pad, dilate))


class _BaseConvRNNCell(RecurrentCell):
    _gate_names = ("",)

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate,
                 i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer,
                 dims, conv_layout, activation):
        super().__init__()
        self._hidden_channels = hidden_channels
        self._input_shape = tuple(input_shape)
        self._conv_layout = conv_layout
        self._activation = activation
        self._dims = dims
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._i2h_pad = _tup(i2h_pad, dims)
        self._i2h_dilate = _tup(i2h_dilate, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        if any(k % 2 == 0 for k in self._h2h_kernel):
            raise ValueError(
                f"h2h_kernel must be odd so the state grid is "
                f"step-invariant, got {h2h_kernel}")
        self._h2h_dilate = _tup(h2h_dilate, dims)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))

        self._channel_axis = conv_layout.find("C")
        channels_last = self._channel_axis != 1
        in_c = self._input_shape[-1 if channels_last else 0]
        spatial = (self._input_shape[:-1] if channels_last
                   else self._input_shape[1:])
        out_spatial = _conv_out(spatial, self._i2h_kernel, self._i2h_pad,
                                self._i2h_dilate)
        total = hidden_channels * len(self._gate_names)
        if channels_last:
            i2h_shape = (total,) + self._i2h_kernel + (in_c,)
            h2h_shape = (total,) + self._h2h_kernel + (hidden_channels,)
            self._state_shape = out_spatial + (hidden_channels,)
        else:
            i2h_shape = (total, in_c) + self._i2h_kernel
            h2h_shape = (total, hidden_channels) + self._h2h_kernel
            self._state_shape = (hidden_channels,) + out_spatial

        self.i2h_weight = Parameter("i2h_weight", shape=i2h_shape,
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight", shape=h2h_shape,
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(total,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(total,),
                                  init=h2h_bias_initializer)

    def _conv_forward(self, x, states):
        i2h = npx.convolution(x, self.i2h_weight.data_for(x),
                              self.i2h_bias.data_for(x),
                              stride=(1,) * self._dims,
                              pad=self._i2h_pad, dilate=self._i2h_dilate,
                              layout=self._conv_layout)
        h2h = npx.convolution(states[0], self.h2h_weight.data_for(x),
                              self.h2h_bias.data_for(x),
                              stride=(1,) * self._dims,
                              pad=self._h2h_pad, dilate=self._h2h_dilate,
                              layout=self._conv_layout)
        return i2h, h2h

    def _act(self, x):
        return npx.activation(x, self._activation)

    def _split_gates(self, x, n):
        return mnp.split(x, n, axis=self._channel_axis)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": self._conv_layout}
                for _ in range(self._num_states)]

    def __repr__(self):
        return (f"{type(self).__name__}({self._input_shape} -> "
                f"{self._hidden_channels}, {self._conv_layout})")


class _ConvRNNMixin:
    _gate_names = ("",)
    _num_states = 1

    def forward(self, x, states):
        i2h, h2h = self._conv_forward(x, states)
        out = self._act(i2h + h2h)
        return out, [out]


class _ConvLSTMMixin:
    _gate_names = ("_i", "_f", "_c", "_o")
    _num_states = 2

    def forward(self, x, states):
        i2h, h2h = self._conv_forward(x, states)
        gates = i2h + h2h
        gi, gf, gc, go = self._split_gates(gates, 4)
        i = npx.sigmoid(gi)
        f = npx.sigmoid(gf)
        o = npx.sigmoid(go)
        c = f * states[1] + i * self._act(gc)
        h = o * self._act(c)
        return h, [h, c]


class _ConvGRUMixin:
    _gate_names = ("_r", "_z", "_o")
    _num_states = 1

    def forward(self, x, states):
        i2h, h2h = self._conv_forward(x, states)
        i2h_r, i2h_z, i2h_o = self._split_gates(i2h, 3)
        h2h_r, h2h_z, h2h_o = self._split_gates(h2h, 3)
        r = npx.sigmoid(i2h_r + h2h_r)
        z = npx.sigmoid(i2h_z + h2h_z)
        cand = self._act(i2h_o + r * h2h_o)
        h = (1.0 - z) * cand + z * states[0]
        return h, [h]


def _make(name, mixin, dims, default_layout):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout=default_layout, activation="tanh"):
        _BaseConvRNNCell.__init__(
            self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
            i2h_pad, i2h_dilate, h2h_dilate,
            i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer,
            dims, conv_layout, activation)

    return type(name, (mixin, _BaseConvRNNCell), {"__init__": __init__})


Conv1DRNNCell = _make("Conv1DRNNCell", _ConvRNNMixin, 1, "NCW")
Conv2DRNNCell = _make("Conv2DRNNCell", _ConvRNNMixin, 2, "NCHW")
Conv3DRNNCell = _make("Conv3DRNNCell", _ConvRNNMixin, 3, "NCDHW")
Conv1DLSTMCell = _make("Conv1DLSTMCell", _ConvLSTMMixin, 1, "NCW")
Conv2DLSTMCell = _make("Conv2DLSTMCell", _ConvLSTMMixin, 2, "NCHW")
Conv3DLSTMCell = _make("Conv3DLSTMCell", _ConvLSTMMixin, 3, "NCDHW")
Conv1DGRUCell = _make("Conv1DGRUCell", _ConvGRUMixin, 1, "NCW")
Conv2DGRUCell = _make("Conv2DGRUCell", _ConvGRUMixin, 2, "NCHW")
Conv3DGRUCell = _make("Conv3DGRUCell", _ConvGRUMixin, 3, "NCDHW")
