"""Vision transforms (reference: gluon/data/vision/transforms/).

Transforms operate on host numpy HWC uint8 images (the loader side), keeping
device work for the batched compute path — the TPU-friendly split.
"""
from __future__ import annotations

import numpy as _np

from ...block import Block
from ...nn.basic_layers import Sequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomLighting"]


def _as_np(x):
    from ....ndarray.ndarray import NDArray

    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


class Compose(Sequential):
    """Chain transforms (reference: transforms.Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t if isinstance(t, Block) else _Fn(t))


class _Fn(Block):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        return self._fn(x)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return _as_np(x).astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: ToTensor)."""

    def forward(self, x):
        x = _as_np(x)
        if x.ndim == 2:
            x = x[:, :, None]
        return (x.astype(_np.float32) / 255.0).transpose(2, 0, 1)


class Normalize(Block):
    """Channel-wise (x - mean) / std on CHW (reference: Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, _np.float32).reshape(-1, 1, 1)
        self._std = _np.asarray(std, _np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        return (_as_np(x) - self._mean) / self._std


def _resize_np(img, size):
    """Nearest+bilinear resize without cv2 (HWC numpy)."""
    import jax
    import jax.numpy as jnp

    h, w = (size, size) if isinstance(size, int) else (size[1], size[0])
    out = jax.image.resize(jnp.asarray(img, jnp.float32),
                           (h, w, img.shape[2]), "bilinear")
    return _np.asarray(out)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):  # noqa: ARG002
        super().__init__()
        self._size = size
        self._keep = keep_ratio

    def forward(self, x):
        x = _as_np(x)
        if x.ndim == 2:
            x = x[:, :, None]
        size = self._size
        if self._keep and isinstance(size, int):
            # reference semantics (image.py:413-415 resize_short): int
            # size + keep_ratio scales the SHORT side to `size` with
            # FLOOR division for the long side
            h, w = x.shape[:2]
            if h < w:
                size = (max(1, size * w // h), size)  # (w, h)
            else:
                size = (size, max(1, size * h // w))
        return _resize_np(x, size)


class CenterCrop(Block):
    def __init__(self, size):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        x = _as_np(x)
        w, h = self._size
        y0 = max((x.shape[0] - h) // 2, 0)
        x0 = max((x.shape[1] - w) // 2, 0)
        return x[y0 : y0 + h, x0 : x0 + w]


class RandomCrop(Block):
    def __init__(self, size, pad=None):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad

    def forward(self, x):
        x = _as_np(x)
        if self._pad:
            p = self._pad
            x = _np.pad(x, ((p, p), (p, p), (0, 0)), mode="constant")
        w, h = self._size
        y0 = _np.random.randint(0, max(x.shape[0] - h, 0) + 1)
        x0 = _np.random.randint(0, max(x.shape[1] - w, 0) + 1)
        return x[y0 : y0 + h, x0 : x0 + w]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):  # noqa: ARG002
        super().__init__()
        self._size = size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        x = _as_np(x)
        area = x.shape[0] * x.shape[1]
        for _ in range(10):
            target = _np.random.uniform(*self._scale) * area
            ar = _np.random.uniform(*self._ratio)
            w = int(round((target * ar) ** 0.5))
            h = int(round((target / ar) ** 0.5))
            if w <= x.shape[1] and h <= x.shape[0]:
                y0 = _np.random.randint(0, x.shape[0] - h + 1)
                x0 = _np.random.randint(0, x.shape[1] - w + 1)
                crop = x[y0 : y0 + h, x0 : x0 + w]
                return _resize_np(crop, self._size)
        return _resize_np(x, self._size)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        x = _as_np(x)
        return x[:, ::-1] if _np.random.rand() < 0.5 else x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        x = _as_np(x)
        return x[::-1] if _np.random.rand() < 0.5 else x


class _Jitter(Block):
    def __init__(self, amount):
        super().__init__()
        self._amount = amount

    def _factor(self):
        return 1.0 + _np.random.uniform(-self._amount, self._amount)


class RandomBrightness(_Jitter):
    def forward(self, x):
        return _np.clip(_as_np(x).astype(_np.float32) * self._factor(),
                        0, 255)


class RandomContrast(_Jitter):
    def forward(self, x):
        x = _as_np(x).astype(_np.float32)
        mean = x.mean()
        return _np.clip((x - mean) * self._factor() + mean, 0, 255)


class RandomSaturation(_Jitter):
    def forward(self, x):
        x = _as_np(x).astype(_np.float32)
        gray = x.mean(axis=-1, keepdims=True)
        return _np.clip((x - gray) * self._factor() + gray, 0, 255)


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference: RandomLighting)."""

    _eigval = _np.array([55.46, 4.794, 1.148], _np.float32)
    _eigvec = _np.array(
        [[-0.5675, 0.7192, 0.4009],
         [-0.5808, -0.0045, -0.814],
         [-0.5836, -0.6948, 0.4203]], _np.float32)

    def __init__(self, alpha=0.1):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        x = _as_np(x).astype(_np.float32)
        a = _np.random.normal(0, self._alpha, 3).astype(_np.float32)
        rgb = (self._eigvec @ (a * self._eigval)).reshape(1, 1, 3)
        return _np.clip(x + rgb, 0, 255)
