"""Vision datasets (reference: gluon/data/vision/datasets.py).

No network egress: datasets read standard on-disk formats (MNIST idx files,
CIFAR binary batches, image folders) from `root`; download=True raises.
`synthetic=True` generates deterministic fake data with the real shapes so
examples/benchmarks run hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as _np

from ..dataset import Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageListDataset",
           "ImageFolderDataset"]


class _DownloadableDataset(Dataset):
    def __init__(self, root, train, transform=None, synthetic=False):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._synthetic = synthetic
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        x = self._data[idx]
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)


class MNIST(_DownloadableDataset):
    """MNIST from idx-ubyte files (reference: datasets.py:MNIST).

    Layout: root/{train,t10k}-{images-idx3,labels-idx1}-ubyte[.gz]
    """

    _IMG = ("train-images-idx3-ubyte", "t10k-images-idx3-ubyte")
    _LBL = ("train-labels-idx1-ubyte", "t10k-labels-idx1-ubyte")
    _SHAPE = (28, 28, 1)
    _CLASSES = 10
    _N_SYNTH = 1024

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None, synthetic=None):
        if synthetic is None:
            synthetic = not self._files_exist(os.path.expanduser(root), train)
        super().__init__(root, train, transform, synthetic)

    @classmethod
    def _files_exist(cls, root, train):
        img = cls._IMG[0 if train else 1]
        return any(os.path.exists(os.path.join(root, img + ext))
                   for ext in ("", ".gz"))

    @staticmethod
    def _read(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            return f.read()

    def _find(self, name):
        for ext in ("", ".gz"):
            p = os.path.join(self._root, name + ext)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(
            f"{name} not found under {self._root}; pass synthetic=True "
            "or place the idx files there (no download egress)")

    def _get_data(self):
        if self._synthetic:
            rng = _np.random.RandomState(42 if self._train else 43)
            n = self._N_SYNTH if self._train else self._N_SYNTH // 4
            self._data = (rng.rand(n, *self._SHAPE) * 255).astype(_np.uint8)
            self._label = rng.randint(0, self._CLASSES, n).astype(_np.int32)
            return
        idx = 0 if self._train else 1
        raw = self._read(self._find(self._IMG[idx]))
        magic, n, rows, cols = struct.unpack(">IIII", raw[:16])
        assert magic == 2051
        self._data = _np.frombuffer(raw, _np.uint8, offset=16).reshape(
            n, rows, cols, 1)
        raw = self._read(self._find(self._LBL[idx]))
        magic, n = struct.unpack(">II", raw[:8])
        assert magic == 2049
        self._label = _np.frombuffer(raw, _np.uint8, offset=8).astype(
            _np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None, synthetic=None):
        super().__init__(root, train, transform, synthetic)


class CIFAR10(_DownloadableDataset):
    """CIFAR-10 from the python/binary batches (reference: CIFAR10)."""

    _SHAPE = (32, 32, 3)
    _CLASSES = 10
    _N_SYNTH = 1024

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None, synthetic=None):
        if synthetic is None:
            synthetic = not os.path.isdir(os.path.expanduser(root))
        super().__init__(root, train, transform, synthetic)

    def _get_data(self):
        if self._synthetic:
            rng = _np.random.RandomState(44 if self._train else 45)
            n = self._N_SYNTH if self._train else self._N_SYNTH // 4
            self._data = (rng.rand(n, *self._SHAPE) * 255).astype(_np.uint8)
            self._label = rng.randint(0, self._CLASSES, n).astype(_np.int32)
            return
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                 if self._train else ["test_batch.bin"])
        data, labels = [], []
        for fname in files:
            path = os.path.join(self._root, fname)
            raw = _np.fromfile(path, _np.uint8)
            rec = 1 + 3072
            raw = raw.reshape(-1, rec)
            labels.append(raw[:, 0].astype(_np.int32))
            imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            data.append(imgs)
        self._data = _np.concatenate(data)
        self._label = _np.concatenate(labels)


class CIFAR100(CIFAR10):
    _CLASSES = 100

    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 fine_label=True, transform=None, synthetic=None):  # noqa: ARG002
        super().__init__(root, train, transform, synthetic)


def _load_image(path, flag):
    """One loader for every file-backed image dataset, matching
    image.imdecode's channel semantics: flag=1 → (H, W, 3) RGB via PIL
    convert('RGB'); flag=0 → (H, W, 1) via convert('L') (ITU-R
    luminosity, NOT a channel mean). .npy files load as stored."""
    if path.endswith(".npy"):
        return _np.load(path)
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError(
            "image decoding requires pillow; use .npy files") from e
    img = Image.open(path)
    if flag == 0:
        return _np.asarray(img.convert("L"))[..., None]
    return _np.asarray(img.convert("RGB"))


class ImageFolderDataset(Dataset):
    """Folder-per-class image dataset (reference: ImageFolderDataset).

    Requires pillow for decoding; .npy files load natively.
    """

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        exts = (".npy", ".png", ".jpg", ".jpeg", ".bmp")
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith(exts):
                    self.items.append((os.path.join(path, fname), label))

    def _load(self, path):
        return _load_image(path, self._flag)

    def __getitem__(self, idx):
        path, label = self.items[idx]
        img = self._load(path)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageRecordDataset(RecordFileDataset):
    """Images + labels from a RecordIO file (reference:
    vision/datasets.py:238 ImageRecordDataset — each record is a packed
    (header, encoded image))."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....image import image as _image
        from ....recordio import unpack

        record = super().__getitem__(idx)
        header, img = unpack(record)
        data = _image.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(data, label)
        return data, label


class ImageListDataset(Dataset):
    """Images given by a .lst file or an in-memory list (reference:
    vision/datasets.py:365 ImageListDataset; .lst format matches
    tools/im2rec.py: idx\\tlabel...\\tpath)."""

    def __init__(self, root=".", imglist=None, flag=1):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self.items = []
        if isinstance(imglist, str):
            with open(os.path.join(self._root, imglist)) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if not parts or parts[0] == "":
                        continue
                    label = _np.asarray([float(v) for v in parts[1:-1]])
                    self.items.append(
                        (os.path.join(self._root, parts[-1]), label))
        elif imglist is not None:
            for entry in imglist:
                label, path = entry[0], entry[-1]
                label = _np.asarray(label, dtype=_np.float64).reshape(-1)
                self.items.append((os.path.join(self._root, path), label))
        else:
            raise ValueError("imglist (file name or list) is required")

    def _load(self, path):
        return _load_image(path, self._flag)

    def __getitem__(self, idx):
        path, label = self.items[idx]
        label = label[0] if label.size == 1 else label
        return self._load(path), label

    def __len__(self):
        return len(self.items)
