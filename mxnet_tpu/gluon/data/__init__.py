"""Data pipeline (reference: python/mxnet/gluon/data/)."""
from . import vision  # noqa: F401
from .dataloader import DataLoader  # noqa: F401
from .dataset import (  # noqa: F401
    ArrayDataset,
    Dataset,
    RecordFileDataset,
    SimpleDataset,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    FilterSampler,
    IntervalSampler,
    RandomSampler,
    Sampler,
    SequentialSampler,
)
