"""Batchify functions (reference: python/mxnet/gluon/data/batchify.py)."""
from __future__ import annotations

import numpy as _np

from ...ndarray.ndarray import NDArray

__all__ = ["Stack", "Pad", "Group", "Append", "AsList",
           "default_batchify_fn"]


def _stack_arrs(arrs):
    from ... import numpy as mnp

    if isinstance(arrs[0], NDArray):
        return mnp.stack(arrs)
    out = _np.stack([_np.asarray(a) for a in arrs])
    return mnp.array(out)


def default_batchify_fn(data):
    """Stack samples; tuples are batchified per-field (reference:
    dataloader.py default_batchify_fn). Dict samples batch per key — an
    extension beyond the reference (which errors on dicts), matching
    the dataset idioms modern pipelines use."""
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn([d[i] for d in data])
                     for i in range(len(data[0])))
    if isinstance(data[0], dict):
        return {k: default_batchify_fn([d[k] for d in data])
                for k in data[0]}
    return _stack_arrs(data)


class Stack:
    def __call__(self, data):
        return _stack_arrs(data)


class Pad:
    """Pad variable-length samples to the batch max shape (reference:
    batchify.Pad — its C++ handle pads EVERY ragged dim to the per-dim
    max, which the reference's own test pins; `axis` is accepted for
    signature compatibility and recorded, but padding is max-shape)."""

    def __init__(self, axis=0, val=0, dtype=None):
        self._axis = axis  # compat only: handle semantics pad all dims
        self._val = val
        self._dtype = dtype

    def __call__(self, data):
        from ... import numpy as mnp

        arrs = [_np.asarray(d) for d in data]
        # pad EVERY dim to the batch max (reference Pad handle pads to
        # the max shape; test_gluon_data.py test_batchify_pad expects
        # (2,4)/(1,3)/(1,2) -> (3,2,4))
        ndim = arrs[0].ndim
        max_shape = [max(a.shape[d] for a in arrs) for d in range(ndim)]
        padded = []
        for a in arrs:
            pad_width = [(0, max_shape[d] - a.shape[d])
                         for d in range(ndim)]
            padded.append(_np.pad(a, pad_width, constant_values=self._val))
        out = _np.stack(padded)
        if self._dtype:
            out = out.astype(self._dtype)
        return mnp.array(out)


class Group:
    """Apply one batchify fn per tuple field (reference: Tuple/Group)."""

    def __init__(self, *fns):
        if len(fns) == 1 and isinstance(fns[0], (list, tuple)):
            fns = fns[0]
        self._fns = fns

    def __call__(self, data):
        assert len(data[0]) == len(self._fns)
        return tuple(fn([d[i] for d in data])
                     for i, fn in enumerate(self._fns))


class Append:
    """Keep samples as separate arrays, optionally expanded with a unit
    batch dim (reference: batchify.Append — for variable-shape data that
    must not be stacked or padded)."""

    def __init__(self, expand=True, batch_axis=0):
        self._expand = expand
        self._batch_axis = batch_axis

    def __call__(self, data):
        from ... import numpy as mnp

        out = []
        for d in data:
            arr = _np.asarray(d)
            if self._expand:
                arr = _np.expand_dims(arr, self._batch_axis)
            out.append(mnp.array(arr))
        return out


class AsList:
    """Return the batch as a plain python list, untouched (reference:
    batchify.AsList — for non-tensor fields like strings)."""

    def __call__(self, data):
        return list(data)
