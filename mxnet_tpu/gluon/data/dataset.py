"""Datasets (reference: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset: __getitem__ + __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([s for s in self if fn(s)])

    def shard(self, num_shards, index):
        """Every num_shards-th sample starting at index (reference:
        Dataset.shard — the multi-worker data split)."""
        assert 0 <= index < num_shards
        items = list(range(index, len(self), num_shards))
        return _SubsetDataset(self, items)

    def take(self, count):
        # None = take everything (reference: Dataset.take)
        n = len(self) if count is None else min(count, len(self))
        return _SubsetDataset(self, list(range(n)))

    def sample(self, sampler):
        """Dataset reordered/subset by a Sampler's indices (reference:
        Dataset.sample, dataset.py:120)."""
        from .sampler import Sampler

        if not isinstance(sampler, Sampler):
            raise TypeError(f"expected Sampler, got {type(sampler)}")
        return _SubsetDataset(self, list(sampler))

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        def first(*sample):
            if len(sample) == 1:
                return fn(sample[0])
            return (fn(sample[0]),) + sample[1:]

        return self.transform(_TupleSpread(first), lazy)


class _TupleSpread:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, sample):
        if isinstance(sample, tuple):
            return self._fn(*sample)
        return self._fn(sample)


class _SubsetDataset(Dataset):
    def __init__(self, dataset, indices):
        self._dataset = dataset
        self._indices = indices

    def __getitem__(self, idx):
        return self._dataset[self._indices[idx]]

    def __len__(self):
        return len(self._indices)


class _LazyTransformDataset(Dataset):
    def __init__(self, dataset, fn):
        self._dataset = dataset
        self._fn = fn

    def __getitem__(self, idx):
        item = self._dataset[idx]
        if isinstance(self._fn, _TupleSpread):
            return self._fn(item)
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)

    def __len__(self):
        return len(self._dataset)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __getitem__(self, idx):
        return self._data[idx]

    def __len__(self):
        return len(self._data)


class ArrayDataset(Dataset):
    """Zip of equal-length arrays (reference: ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        for a in args:
            assert len(a) == self._length
        self._data = args

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference: RecordFileDataset over
    dmlc RecordIO; here over mxnet_tpu.recordio.RecordFile)."""

    def __init__(self, filename):
        import os

        from ...recordio import IndexedRecordIO

        idx_path = os.path.splitext(filename)[0] + ".idx"
        if not os.path.exists(idx_path):
            # a missing sidecar would otherwise read as an EMPTY dataset
            raise FileNotFoundError(
                f"RecordFileDataset requires the index sidecar "
                f"{idx_path!r} (build it with tools/im2rec.py)")
        self._record = IndexedRecordIO(filename)

    def __getitem__(self, idx):
        # positional indexing: record KEYS need not be 0-based (im2rec
        # keeps .lst keys), so map position -> key like the reference
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record)
