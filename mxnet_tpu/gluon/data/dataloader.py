"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py:123-305).

The reference's multiprocessing workers + shared-memory NDArray pickling are
a CPU-side mechanism; the TPU-native pipeline keeps batches as host numpy
until the last moment and lets `device_put` (async) overlap H2D with compute.
num_workers>0 uses a thread pool (the GIL is released in numpy/decode work;
TPU input pipelines are rarely Python-bound the way OpenCV-on-CPU was) and a
prefetch queue mirroring iter_prefetcher.h.
"""
from __future__ import annotations

import queue
import threading

import numpy as _np

from .batchify import default_batchify_fn
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120, try_nopython=None):  # noqa: ARG002
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required without batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle and sampler are exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        from ... import _native
        if _native.available():
            yield from self._native_iter()
        else:
            yield from self._threaded_iter()

    def _native_iter(self):
        """Native ordered pipeline: batches decode on C++ worker threads
        (num_workers wide), pop in order with back-pressure
        (native/mxtpu_runtime.cc Pipeline; reference: _MultiWorkerIter)."""
        from ... import _native

        batches = list(self._batch_sampler)
        pipe = _native.NativePipeline(
            num_threads=self._num_workers,
            capacity=max(self._prefetch, self._num_workers))
        try:
            submitted = 0
            popped = 0
            # prime the pipeline, then steady-state: pop one / push one
            while popped < len(batches):
                while (submitted < len(batches)
                       and submitted - popped < max(self._prefetch, 1)):
                    indices = batches[submitted]
                    pipe.submit(lambda ix=indices: self._make_batch(ix))
                    submitted += 1
                try:
                    yield pipe.pop(timeout=self._timeout)
                except TimeoutError:
                    # a hung worker can't be joined — abandon, not close
                    pipe.abandon()
                    raise
                popped += 1
        finally:
            pipe.close()

    def _threaded_iter(self):
        """Prefetching thread pool (the iter_prefetcher.h analog)."""
        batches = list(self._batch_sampler)
        out_q = queue.Queue(maxsize=max(self._prefetch, 1))
        stop = threading.Event()

        def producer():
            try:
                for indices in batches:
                    if stop.is_set():
                        return
                    out_q.put(self._make_batch(indices))
            except Exception as e:  # propagate to consumer
                out_q.put(e)
            finally:
                out_q.put(StopIteration)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = out_q.get(timeout=self._timeout)
                if item is StopIteration:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()

    def __len__(self):
        return len(self._batch_sampler)
