"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py:123-305).

Worker modes (matching the reference's semantics):
  * num_workers=0 — synchronous in the caller.
  * num_workers>0 (default) — multiprocessing fork workers, like the
    reference's _MultiWorkerIter: each worker loads + batchifies to plain
    numpy in its own interpreter (PIL decode and augmenters hold the GIL,
    so processes are the only way decode scales — measured in
    benchmark/pipeline.py); the parent converts to device arrays so
    children never touch jax/the TPU tunnel.
  * num_workers>0, thread_pool=True — prefetching thread pool over the
    native C++ pipeline (iter_prefetcher.h analog): right when samples
    are already numpy (no GIL-bound decode) or datasets are unpicklable.
"""
from __future__ import annotations

import multiprocessing as _mp
import queue
import threading

import numpy as _np

from ...diagnostics import spans as _spans
from ...telemetry import instruments as _telemetry
from .batchify import default_batchify_fn
from .sampler import BatchSampler, RandomSampler, SequentialSampler


# --- multiprocessing worker plumbing (reference: worker_loop,
# dataloader.py:123-305; fork start method inherits the dataset copy-on-
# write, so nothing is pickled per batch except indices out / batch back)
_WORKER_DATASET = None


def _mp_worker_init(dataset):
    global _WORKER_DATASET
    _WORKER_DATASET = dataset


def _mp_worker_fn(indices):
    """Load samples in the child; collation happens in the parent with the
    user's batchify_fn (children never create device arrays — jax stays
    un-initialized there)."""
    return [_WORKER_DATASET[i] for i in indices]

__all__ = ["DataLoader", "default_batchify_fn"]


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120, try_nopython=None,  # noqa: ARG002
                 device_prefetch=None):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required without batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle and sampler are exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None \
                or last_batch is not None:
            # reference dataloader.py: batch_sampler owns the batching —
            # a conflicting spec is an error, not silently ignored
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not "
                "be specified if batch_sampler is specified")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._thread_pool = bool(thread_pool)
        self._mp_pool = None       # persistent worker pool (mp mode)
        self._fork_safe_cache = None
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        # device_prefetch: keep up to N batches BEYOND the one being
        # consumed already jax.device_put to the accelerator, so the next
        # batch's host->device transfer rides the async dispatch stream
        # UNDER the current step's compute (double-buffered input
        # pipeline; docs/data.md). None defers to MXTPU_DEVICE_PREFETCH.
        self._device_prefetch = device_prefetch
        self._batchify_fn = batchify_fn or default_batchify_fn

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        # span-wrap each fetch so the diagnostics step table shows the
        # 'data' phase: time the training loop spends waiting on a batch
        # (pipeline-starved steps show up here, whatever the worker mode)
        it = self._iter_impl()
        depth = self._device_prefetch
        if depth is None:
            from ... import env as _env

            depth = _env.get("MXTPU_DEVICE_PREFETCH")
        if depth and depth > 0:
            it = self._device_prefetch_iter(it, int(depth))
        while True:
            with _spans.span("dataloader_next", cat="data"):
                try:
                    batch = next(it)
                except StopIteration:
                    return
            yield batch

    @staticmethod
    def _to_device(batch):
        """Start the batch's host->device transfer (async device_put):
        NDArray leaves re-wrap their device array, numpy leaves become
        NDArrays on device (the h2d bytes telemetry counts). Containers
        keep their shape, so delivered batches only differ from the
        un-prefetched loader by already living on the accelerator."""
        import jax

        from ...ndarray.ndarray import NDArray

        def put(x):
            if isinstance(x, NDArray):
                return NDArray(jax.device_put(x._data))
            if isinstance(x, _np.ndarray):
                _telemetry.record_transfer("h2d", x.nbytes)
                return NDArray(jax.device_put(x))
            return x

        def walk(x):
            if isinstance(x, tuple):
                return tuple(walk(v) for v in x)
            if isinstance(x, list):
                return [walk(v) for v in x]
            if isinstance(x, dict):
                return {k: walk(v) for k, v in x.items()}
            return put(x)

        return walk(batch)

    def _device_prefetch_iter(self, it, depth):
        """Double-buffered device prefetch: hold the next `depth` batches
        with their device_put already ISSUED while the consumer runs the
        current step — device_put is async, so the copies overlap the
        step's compute and next(loader) returns transferred arrays
        instead of starting a transfer (docs/data.md, docs/telemetry.md:
        data_prefetch_total / data_prefetch_depth)."""
        import collections

        pending = collections.deque()

        def top_up():
            while len(pending) <= depth:
                try:
                    nxt = next(it)
                except StopIteration:
                    return
                with _spans.span("device_prefetch", cat="data"):
                    pending.append(self._to_device(nxt))
                _telemetry.record_device_prefetch(len(pending))

        top_up()
        while pending:
            batch = pending.popleft()
            # issue the NEXT transfers before handing this batch out —
            # they run on the async stream while the consumer computes
            top_up()
            yield batch

    def _iter_impl(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        if not self._thread_pool and self._fork_safe():
            yield from self._mp_iter()
            return
        from ... import _native
        if _native.available():
            yield from self._native_iter()
        else:
            yield from self._threaded_iter()

    def _fork_safe(self):
        """Fork workers must never touch jax (initialized jax is not
        fork-safe; over the TPU tunnel a forked child can wedge it).
        Probe one sample in the parent: datasets yielding device arrays
        fall back to the threaded/native path."""
        from ...ndarray.ndarray import NDArray

        def has_nd(x):
            if isinstance(x, (tuple, list)):
                return any(has_nd(i) for i in x)
            if isinstance(x, dict):  # dict samples batch per key now
                return any(has_nd(v) for v in x.values())
            return isinstance(x, NDArray)

        if self._fork_safe_cache is None:
            try:
                self._fork_safe_cache = (len(self._dataset) == 0
                                         or not has_nd(self._dataset[0]))
            except Exception:
                self._fork_safe_cache = False
        return self._fork_safe_cache

    def _mp_iter(self):
        """Multiprocessing workers (the reference's default mode,
        _MultiWorkerIter). Workers load samples; the parent collates with
        the user batchify_fn and device-puts (async H2D overlaps compute).
        Submission is windowed to `prefetch` outstanding batches
        (back-pressure, like iter_prefetcher.h) with the loader timeout."""
        import collections

        batches = list(self._batch_sampler)
        if not batches:
            return
        pool = self._ensure_pool()
        window = max(self._prefetch, 1)
        pending = collections.deque()
        try:
            submitted = 0
            while pending or submitted < len(batches):
                while submitted < len(batches) and len(pending) < window:
                    pending.append(pool.apply_async(
                        _mp_worker_fn, (batches[submitted],)))
                    submitted += 1
                samples = pending.popleft().get(timeout=self._timeout)
                yield self._batchify_fn(samples)
        except Exception:
            self._shutdown_pool()  # hung/broken workers: don't reuse
            raise

    def _ensure_pool(self):
        """Persistent worker pool, created on first epoch and reused for
        the loader's lifetime (reference: _MultiWorkerIter keeps its
        workers alive across epochs)."""
        if self._mp_pool is not None:
            return self._mp_pool
        # fork is cheap (COW dataset) but risky from a multi-threaded
        # parent (the reference accepted the same trade-off — its workers
        # fork after MXNet init). USER Python threads force spawn; jax's
        # internal threads only warn, since workers never call jax.
        # Framework service threads (all named "mxtpu-*": the watchdog
        # scanner, serving batcher, prefetch producers) don't gate the
        # choice either — a long-lived observability thread must not
        # silently flip every loader to spawn (which also requires
        # picklable datasets). That exemption is safe because the
        # subsystems those threads hold locks in (flight recorder,
        # telemetry registry, span ring, watchdog) reinstall fresh locks
        # via os.register_at_fork(after_in_child=...), so user dataset
        # code touching NDArray ops or telemetry in a forked worker
        # can't inherit a lock a service thread held mid-fork. Set
        # MXTPU_MP_START=spawn for full isolation. MXTPU_MP_START
        # overrides the heuristic either way.
        from ... import env as _env

        user_threads = [
            t for t in threading.enumerate()
            if t is not threading.main_thread()
            and not t.name.startswith("mxtpu-")]
        start = _env.get("MXTPU_MP_START") or (
            "fork" if not user_threads else "spawn")
        ctx = _mp.get_context(start)
        self._mp_pool = ctx.Pool(self._num_workers,
                                 initializer=_mp_worker_init,
                                 initargs=(self._dataset,))
        return self._mp_pool

    def _shutdown_pool(self):
        if self._mp_pool is not None:
            self._mp_pool.terminate()
            self._mp_pool.join()
            self._mp_pool = None

    def __del__(self):
        try:
            self._shutdown_pool()
        except Exception:
            pass

    def _native_iter(self):
        """Native ordered pipeline: batches decode on C++ worker threads
        (num_workers wide), pop in order with back-pressure
        (native/mxtpu_runtime.cc Pipeline; reference: _MultiWorkerIter)."""
        from ... import _native

        batches = list(self._batch_sampler)
        pipe = _native.NativePipeline(
            num_threads=self._num_workers,
            capacity=max(self._prefetch, self._num_workers))
        try:
            submitted = 0
            popped = 0
            # prime the pipeline, then steady-state: pop one / push one
            while popped < len(batches):
                while (submitted < len(batches)
                       and submitted - popped < max(self._prefetch, 1)):
                    indices = batches[submitted]
                    pipe.submit(lambda ix=indices: self._make_batch(ix))
                    submitted += 1
                try:
                    yield pipe.pop(timeout=self._timeout)
                except TimeoutError:
                    # a hung worker can't be joined — abandon, not close
                    pipe.abandon()
                    raise
                popped += 1
        finally:
            pipe.close()

    def _threaded_iter(self):
        """Prefetching thread pool (the iter_prefetcher.h analog)."""
        batches = list(self._batch_sampler)
        out_q = queue.Queue(maxsize=max(self._prefetch, 1))
        stop = threading.Event()

        def producer():
            try:
                for indices in batches:
                    if stop.is_set():
                        return
                    out_q.put(self._make_batch(indices))
            except Exception as e:  # propagate to consumer
                out_q.put(e)
            finally:
                out_q.put(StopIteration)

        t = threading.Thread(target=producer, name="mxtpu-data-producer",
                             daemon=True)
        t.start()
        try:
            while True:
                item = out_q.get(timeout=self._timeout)
                if item is StopIteration:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()

    def __len__(self):
        return len(self._batch_sampler)
