"""Inception v3 (reference: model_zoo/vision/inception.py)."""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock


def _conv(channels, kernel, stride=1, pad=0):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, kernel, stride, pad, use_bias=False))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Concur(HybridBlock):
    """Run branches on the same input, concat on channels."""

    def __init__(self, *branches):
        super().__init__()
        for b in branches:
            self.register_child(b)

    def forward(self, x):
        from .... import numpy as np

        return np.concatenate([b(x) for b in self._children.values()], axis=1)


def _branch(*stages):
    out = nn.HybridSequential()
    for s in stages:
        out.add(s)
    return out


def _make_A(pool_features):
    return _Concur(
        _branch(_conv(64, 1)),
        _branch(_conv(48, 1), _conv(64, 5, pad=2)),
        _branch(_conv(64, 1), _conv(96, 3, pad=1), _conv(96, 3, pad=1)),
        _branch(nn.AvgPool2D(3, 1, 1), _conv(pool_features, 1)),
    )


def _make_B():
    return _Concur(
        _branch(_conv(384, 3, 2)),
        _branch(_conv(64, 1), _conv(96, 3, pad=1), _conv(96, 3, 2)),
        _branch(nn.MaxPool2D(3, 2)),
    )


def _make_C(channels_7x7):
    c = channels_7x7
    return _Concur(
        _branch(_conv(192, 1)),
        _branch(_conv(c, 1), _conv(c, (1, 7), pad=(0, 3)),
                _conv(192, (7, 1), pad=(3, 0))),
        _branch(_conv(c, 1), _conv(c, (7, 1), pad=(3, 0)),
                _conv(c, (1, 7), pad=(0, 3)), _conv(c, (7, 1), pad=(3, 0)),
                _conv(192, (1, 7), pad=(0, 3))),
        _branch(nn.AvgPool2D(3, 1, 1), _conv(192, 1)),
    )


def _make_D():
    return _Concur(
        _branch(_conv(192, 1), _conv(320, 3, 2)),
        _branch(_conv(192, 1), _conv(192, (1, 7), pad=(0, 3)),
                _conv(192, (7, 1), pad=(3, 0)), _conv(192, 3, 2)),
        _branch(nn.MaxPool2D(3, 2)),
    )


def _make_E():
    return _Concur(
        _branch(_conv(320, 1)),
        _branch(_conv(384, 1),
                _Concur(_branch(_conv(384, (1, 3), pad=(0, 1))),
                        _branch(_conv(384, (3, 1), pad=(1, 0))))),
        _branch(_conv(448, 1), _conv(384, 3, pad=1),
                _Concur(_branch(_conv(384, (1, 3), pad=(0, 1))),
                        _branch(_conv(384, (3, 1), pad=(1, 0))))),
        _branch(nn.AvgPool2D(3, 1, 1), _conv(192, 1)),
    )


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):  # noqa: ARG002
        super().__init__()
        self.features = nn.HybridSequential()
        self.features.add(_conv(32, 3, 2))
        self.features.add(_conv(32, 3))
        self.features.add(_conv(64, 3, pad=1))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(_conv(80, 1))
        self.features.add(_conv(192, 3))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_make_E())
        self.features.add(_make_E())
        self.features.add(nn.AvgPool2D(8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("no pretrained weights bundled")
    kwargs.pop("ctx", None)
    kwargs.pop("root", None)
    return Inception3(**kwargs)
