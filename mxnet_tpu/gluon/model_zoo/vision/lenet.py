"""LeNet-5 for MNIST — BASELINE.json config #1's model."""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock


class LeNet(HybridBlock):
    """Classic LeNet (conv-pool x2 + dense x3), NCHW 28x28 inputs."""

    def __init__(self, classes=10, **kwargs):  # noqa: ARG002
        super().__init__()
        self.features = nn.HybridSequential()
        self.features.add(
            nn.Conv2D(6, kernel_size=5, padding=2, activation="tanh"),
            nn.AvgPool2D(pool_size=2, strides=2),
            nn.Conv2D(16, kernel_size=5, activation="tanh"),
            nn.AvgPool2D(pool_size=2, strides=2),
            nn.Flatten(),
            nn.Dense(120, activation="tanh"),
            nn.Dense(84, activation="tanh"),
        )
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def lenet(classes=10, pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("no pretrained weights bundled")
    return LeNet(classes=classes, **kwargs)
