"""AlexNet (reference: model_zoo/vision/alexnet.py)."""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):  # noqa: ARG002
        super().__init__()
        self.features = nn.HybridSequential()
        self.features.add(
            nn.Conv2D(64, 11, 4, 2, activation="relu"),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 5, padding=2, activation="relu"),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(384, 3, padding=1, activation="relu"),
            nn.Conv2D(256, 3, padding=1, activation="relu"),
            nn.Conv2D(256, 3, padding=1, activation="relu"),
            nn.MaxPool2D(3, 2),
            nn.Flatten(),
            nn.Dense(4096, activation="relu"),
            nn.Dropout(0.5),
            nn.Dense(4096, activation="relu"),
            nn.Dropout(0.5),
        )
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("no pretrained weights bundled")
    kwargs.pop("ctx", None)
    kwargs.pop("root", None)
    return AlexNet(**kwargs)
