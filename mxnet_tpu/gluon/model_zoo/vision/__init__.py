"""Vision model zoo (reference: python/mxnet/gluon/model_zoo/vision/:
alexnet, densenet, inception, mobilenet, resnet, squeezenet, vgg).

Pretrained-weight download is not available (no egress); `pretrained=True`
raises with a pointer to load_parameters.
"""
from .alexnet import AlexNet, alexnet  # noqa: F401
from .densenet import (  # noqa: F401
    DenseNet,
    densenet121,
    densenet161,
    densenet169,
    densenet201,
)
from .inception import Inception3, inception_v3  # noqa: F401
from .lenet import LeNet, lenet  # noqa: F401
from .mobilenet import (  # noqa: F401
    MobileNet,
    MobileNetV2,
    get_mobilenet,
    get_mobilenet_v2,
    mobilenet0_25,
    mobilenet0_5,
    mobilenet0_75,
    mobilenet1_0,
    mobilenet_v2_0_25,
    mobilenet_v2_0_5,
    mobilenet_v2_0_75,
    mobilenet_v2_1_0,
)
from .resnet import (  # noqa: F401
    ResNetV1,
    ResNetV2,
    get_resnet,
    resnet18_v1,
    resnet18_v2,
    resnet34_v1,
    resnet34_v2,
    resnet50_v1,
    resnet50_v2,
    resnet101_v1,
    resnet101_v2,
    resnet152_v1,
    resnet152_v2,
)
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1  # noqa: F401
from .vgg import (  # noqa: F401
    VGG,
    get_vgg,
    vgg11,
    vgg11_bn,
    vgg13,
    vgg13_bn,
    vgg16,
    vgg16_bn,
    vgg19,
    vgg19_bn,
)

_MODELS = {}


def _register_models():
    import sys

    mod = sys.modules[__name__]
    for name in ["alexnet", "densenet121", "densenet161", "densenet169",
                 "densenet201", "inception_v3", "lenet",
                 "mobilenet0_25", "mobilenet0_5", "mobilenet0_75",
                 "mobilenet1_0", "mobilenet_v2_0_25", "mobilenet_v2_0_5",
                 "mobilenet_v2_0_75", "mobilenet_v2_1_0",
                 "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
                 "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
                 "resnet101_v2", "resnet152_v2", "squeezenet1_0",
                 "squeezenet1_1", "vgg11", "vgg11_bn", "vgg13", "vgg13_bn",
                 "vgg16", "vgg16_bn", "vgg19", "vgg19_bn"]:
        _MODELS[name] = getattr(mod, name)


_register_models()


def get_model(name, **kwargs):
    """Create a model by name (reference: model_zoo/vision/__init__.py)."""
    name = name.lower()
    if name not in _MODELS:
        raise ValueError(
            f"unknown model '{name}'; available: {sorted(_MODELS)}")
    return _MODELS[name](**kwargs)
