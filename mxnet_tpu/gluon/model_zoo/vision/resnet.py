"""ResNet v1/v2 (reference: python/mxnet/gluon/model_zoo/vision/resnet.py).

Same architecture family (basic/bottleneck blocks, 18/34/50/101/152 layers)
built from this framework's layers. Designed for TPU: pass layout="NHWC"
(channels-last — C rides the MXU lane dimension, measured ~10% faster than
NCHW on v5e) or keep the reference default NCHW; train in bf16 via
net.cast('bfloat16').
"""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock


def _bn(layout, **kw):
    return nn.BatchNorm(axis=1 if layout[1] == "C" else -1, **kw)


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError(
            "pretrained weights are not bundled (no network egress); "
            "use net.load_parameters(path) with a local checkpoint")


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                                in_channels=in_channels, layout=layout))
        self.body.add(_bn(layout))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                                in_channels=channels, layout=layout))
        self.body.add(_bn(layout))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, 1, stride,
                                          use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(_bn(layout))
        else:
            self.downsample = None
        self.relu = nn.Activation("relu")

    def forward(self, x):
        residual = x
        out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu(out + residual)


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, 1, stride, use_bias=False,
                                layout=layout))
        self.body.add(_bn(layout))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels // 4, 3, 1, 1, use_bias=False,
                                layout=layout))
        self.body.add(_bn(layout))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 1, 1, use_bias=False,
                                layout=layout))
        self.body.add(_bn(layout))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, 1, stride,
                                          use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(_bn(layout))
        else:
            self.downsample = None
        self.relu = nn.Activation("relu")

    def forward(self, x):
        residual = x
        out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu(out + residual)


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        self.bn1 = _bn(layout)
        self.conv1 = nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                               in_channels=in_channels, layout=layout)
        self.bn2 = _bn(layout)
        self.conv2 = nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                               in_channels=channels, layout=layout)
        self.relu = nn.Activation("relu")
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.relu(self.bn2(x))
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        self.bn1 = _bn(layout)
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False,
                               layout=layout)
        self.bn2 = _bn(layout)
        self.conv2 = nn.Conv2D(channels // 4, 3, stride, 1, use_bias=False,
                               layout=layout)
        self.bn3 = _bn(layout)
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False, layout=layout)
        self.relu = nn.Activation("relu")
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.relu(self.bn2(x))
        x = self.conv2(x)
        x = self.relu(self.bn3(x))
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW"):
        super().__init__()
        assert len(layers) == len(channels) - 1
        self._layout = layout
        self.features = nn.HybridSequential()
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                        use_bias=False, layout=layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False, layout=layout))
            self.features.add(_bn(layout))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i]))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=self._layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=self._layout))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW"):
        super().__init__()
        assert len(layers) == len(channels) - 1
        self._layout = layout
        self.features = nn.HybridSequential()
        self.features.add(_bn(layout, scale=False, center=False))
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                        use_bias=False, layout=layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False, layout=layout))
            self.features.add(_bn(layout))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels))
            in_channels = channels[i + 1]
        self.features.add(_bn(layout))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=self._layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=self._layout))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


_resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]
_net_versions = [ResNetV1, ResNetV2]


def get_resnet(version, num_layers, pretrained=False, device=None, **kwargs):
    _no_pretrained(pretrained)
    kwargs.pop("ctx", None)
    kwargs.pop("root", None)
    block_type, layers, channels = _resnet_spec[num_layers]
    resnet_class = _net_versions[version - 1]
    block_class = _block_versions[version - 1][block_type]
    return resnet_class(block_class, layers, channels, **kwargs)


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
