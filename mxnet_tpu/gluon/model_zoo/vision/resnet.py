"""ResNet v1/v2 (reference: python/mxnet/gluon/model_zoo/vision/resnet.py).

Same architecture family (basic/bottleneck blocks, 18/34/50/101/152 layers)
built from this framework's layers. Designed for TPU: pass layout="NHWC"
(channels-last — C rides the MXU lane dimension, measured ~10% faster than
NCHW on v5e) or keep the reference default NCHW; train in bf16 via
net.cast('bfloat16').
"""
from __future__ import annotations

from ... import nn
from ....ndarray.ndarray import apply_op
from ...block import HybridBlock
from ...parameter import Parameter


def _bn(layout, **kw):
    return nn.BatchNorm(axis=1 if layout[1] == "C" else -1, **kw)


class SpaceToDepthStem(HybridBlock):
    """7×7/s2 ResNet stem computed as a 4×4/s1 conv over 2×2
    space-to-depth input (the MLPerf TPU trick).

    The raw 7×7×3 conv leaves the MXU's 128-lane contraction dimension
    ~97% idle (3 input channels). Repacking 2×2 input pixels into
    channels gives an exactly equivalent conv with 12 input channels and
    a 4×4 kernel (variance: out(i)=Σ_k w[k]·x[2i+k−3]; writing
    k−3=2m+a splits the taps across s2d phase a and spatial offset m).

    The parameter KEEPS the reference (O,7,7,C)/(O,C,7,7) shape so
    checkpoints map 1:1; the repack runs inside the jitted step (9K
    elements — free). Only 2×-stride 7×7 stems with even input sizes are
    supported, which is the only place it's used.
    """

    def __init__(self, channels, in_channels=3, layout="NHWC"):
        super().__init__()
        if layout[-1] != "C":
            raise ValueError("SpaceToDepthStem requires a channels-last "
                             "layout (got %r)" % layout)
        self._channels = channels
        self.weight = Parameter("weight",
                                shape=(channels, 7, 7, in_channels),
                                allow_deferred_init=True)

    def forward(self, x):
        def _s2d_conv(x, w):
            import jax.numpy as jnp
            from jax import lax

            n, h, wd, c = x.shape
            o = w.shape[0]
            # input: (N,H,W,C) -> (N,H/2,W/2,4C), packed (ah, aw, c)
            xs = x.reshape(n, h // 2, 2, wd // 2, 2, c)
            xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(
                n, h // 2, wd // 2, 4 * c)
            # kernel: (O,7,7,C) -> pad one leading zero tap per spatial
            # dim (tap index kh+1 = 2·km+a) -> (O,4,4,4C), same packing
            wp = jnp.pad(w, ((0, 0), (1, 0), (1, 0), (0, 0)))
            wp = wp.reshape(o, 4, 2, 4, 2, c)
            wp = wp.transpose(0, 1, 3, 2, 4, 5).reshape(o, 4, 4, 4 * c)
            dn = lax.conv_dimension_numbers(
                xs.shape, wp.shape, ("NHWC", "OHWI", "NHWC"))
            return lax.conv_general_dilated(
                xs, wp, window_strides=(1, 1),
                padding=((2, 1), (2, 1)), dimension_numbers=dn)

        if self.weight._is_deferred:
            self.weight._finish_deferred_init(
                (self._channels, 7, 7, x.shape[-1]))
        return apply_op(_s2d_conv, x, self.weight.data_for(x),
                        name="stem_s2d_conv")


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError(
            "pretrained weights are not bundled (no network egress); "
            "use net.load_parameters(path) with a local checkpoint")


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                                in_channels=in_channels, layout=layout))
        self.body.add(_bn(layout))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                                in_channels=channels, layout=layout))
        self.body.add(_bn(layout))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, 1, stride,
                                          use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(_bn(layout))
        else:
            self.downsample = None
        self.relu = nn.Activation("relu")

    def forward(self, x):
        residual = x
        out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu(out + residual)


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, 1, stride, use_bias=False,
                                layout=layout))
        self.body.add(_bn(layout))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels // 4, 3, 1, 1, use_bias=False,
                                layout=layout))
        self.body.add(_bn(layout))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 1, 1, use_bias=False,
                                layout=layout))
        self.body.add(_bn(layout))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, 1, stride,
                                          use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(_bn(layout))
        else:
            self.downsample = None
        self.relu = nn.Activation("relu")

    def forward(self, x):
        residual = x
        out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu(out + residual)


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        self.bn1 = _bn(layout)
        self.conv1 = nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                               in_channels=in_channels, layout=layout)
        self.bn2 = _bn(layout)
        self.conv2 = nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                               in_channels=channels, layout=layout)
        self.relu = nn.Activation("relu")
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.relu(self.bn2(x))
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        self.bn1 = _bn(layout)
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False,
                               layout=layout)
        self.bn2 = _bn(layout)
        self.conv2 = nn.Conv2D(channels // 4, 3, stride, 1, use_bias=False,
                               layout=layout)
        self.bn3 = _bn(layout)
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False, layout=layout)
        self.relu = nn.Activation("relu")
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.relu(self.bn2(x))
        x = self.conv2(x)
        x = self.relu(self.bn3(x))
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", stem_s2d=False):
        super().__init__()
        assert len(layers) == len(channels) - 1
        self._layout = layout
        self.features = nn.HybridSequential()
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                        use_bias=False, layout=layout))
        else:
            if stem_s2d:
                self.features.add(SpaceToDepthStem(channels[0],
                                                   layout=layout))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False, layout=layout))
            self.features.add(_bn(layout))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i]))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=self._layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=self._layout))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", stem_s2d=False):
        super().__init__()
        assert len(layers) == len(channels) - 1
        self._layout = layout
        self.features = nn.HybridSequential()
        self.features.add(_bn(layout, scale=False, center=False))
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                        use_bias=False, layout=layout))
        else:
            if stem_s2d:
                self.features.add(SpaceToDepthStem(channels[0],
                                                   layout=layout))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False, layout=layout))
            self.features.add(_bn(layout))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels))
            in_channels = channels[i + 1]
        self.features.add(_bn(layout))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=self._layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=self._layout))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


_resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]
_net_versions = [ResNetV1, ResNetV2]


def get_resnet(version, num_layers, pretrained=False, device=None, **kwargs):
    _no_pretrained(pretrained)
    kwargs.pop("ctx", None)
    kwargs.pop("root", None)
    block_type, layers, channels = _resnet_spec[num_layers]
    resnet_class = _net_versions[version - 1]
    block_class = _block_versions[version - 1][block_type]
    return resnet_class(block_class, layers, channels, **kwargs)


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
