"""Pretrained-weight store (reference: gluon/model_zoo/model_store.py —
short_hash / get_model_file / purge over an S3-backed cache).

TPU re-design note: this environment has no network egress, so the store
resolves ONLY against the local cache root (MXNET_HOME, default
~/.mxnet/models) — same directory layout and filename convention
(`<name>-<8-char-hash>.params`) as the reference, so caches populated by
reference tooling are picked up directly.
"""
import os

__all__ = ["get_model_file", "purge"]

# model name -> 8-char content hash prefix (reference: _model_sha1).
# Entries appear here when golden checkpoints ship in the local cache;
# unknown models still resolve by filename glob below.
_model_sha1 = {}


def short_hash(name):
    """8-char hash prefix for a registered model name (reference:
    model_store.py short_hash)."""
    if name not in _model_sha1:
        raise ValueError(
            f"Pretrained model for {name} is not available.")
    return _model_sha1[name][:8]


def _root():
    return os.path.expanduser(
        os.environ.get("MXNET_HOME", os.path.join("~", ".mxnet", "models")))


def get_model_file(name, root=None):
    """Locate `<name>-<hash>.params` in the local cache (reference:
    model_store.py get_model_file; download is not available here —
    zero-egress environment — so a missing file raises with the path the
    user should place weights at)."""
    root = os.path.expanduser(root or _root())
    if name in _model_sha1:
        path = os.path.join(root, f"{name}-{short_hash(name)}.params")
        if os.path.exists(path):
            return path
    if os.path.isdir(root):
        import glob

        hits = sorted(glob.glob(os.path.join(root, f"{name}-????????.params")))
        if hits:
            return hits[-1]
    raise FileNotFoundError(
        f"no cached weights for {name!r} under {root}; this environment "
        f"has no network egress — place <name>-<hash>.params there "
        f"manually (reference layout)")


def purge(root=None):
    """Remove all cached model files (reference: model_store.py purge)."""
    root = os.path.expanduser(root or _root())
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
