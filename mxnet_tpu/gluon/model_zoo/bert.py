"""BERT model family (driver config #4: BERT-base SQuAD fine-tune, bf16).

The reference ecosystem ships BERT through gluon-nlp on top of MXNet's
Gluon layers; this module provides the same Gluon-style surface natively:
`BERTModel` (+ `BERTEncoder`, `MultiHeadAttention`, `PositionwiseFFN`),
task heads (`BERTClassifier`, `BERTForQA`, masked-LM decoder), and the
standard configs `bert_12_768_12` / `bert_24_1024_16`.

TPU-first design choices:
  * fused QKV projection — one (D, 3D) matmul keeps the MXU busy instead
    of three small gemms;
  * attention scores via einsum, additive -1e9 masking (no boolean
    select), softmax in fp32 even under bf16 activations;
  * everything is a HybridBlock: one `hybridize()` compiles the whole
    encoder into a single XLA program, with bf16 via amp
    convert_hybrid_block or dtype="bfloat16" construction;
  * sequence dim is shardable: attention/FFN are batch-pointwise, so
    pjit sharding specs (dp on batch, sp via parallel.ring_attention
    for long sequences) drop in without model changes.
"""
from __future__ import annotations

import math

import numpy as _np

from ... import numpy as np
from ... import numpy_extension as npx
from ..block import HybridBlock
from ..nn import Dense, Dropout, Embedding, HybridSequential, LayerNorm

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "BERTEncoder", "BERTModel", "BERTClassifier", "BERTForQA",
           "bert_12_768_12", "bert_24_1024_16", "get_bert_model"]


def _flash_enabled():
    from ... import env as _env

    return _env.get("MXTPU_FLASH_ATTENTION")


def _is_training():
    from ... import autograd as _ag

    return bool(_ag.is_training())


class MultiHeadAttention(HybridBlock):
    """Self-attention with fused QKV projection."""

    def __init__(self, units, num_heads, dropout=0.0, dtype="float32"):
        super().__init__()
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by "
                             f"num_heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._head_dim = units // num_heads
        self.qkv = Dense(3 * units, flatten=False, dtype=dtype,
                         in_units=units)
        self.out_proj = Dense(units, flatten=False, dtype=dtype,
                              in_units=units)
        self.dropout = Dropout(dropout)

    def forward(self, x, mask=None):
        # x: (B, S, D); mask: (B, S) or (B, S, S), both 1=valid/0=masked
        b, s, _ = x.shape
        h, d = self._num_heads, self._head_dim
        qkv = self.qkv(x).reshape((b, s, 3, h, d))
        q = qkv[:, :, 0].transpose((0, 2, 1, 3))  # (B, H, S, d)
        k = qkv[:, :, 1].transpose((0, 2, 1, 3))
        v = qkv[:, :, 2].transpose((0, 2, 1, 3))
        drop_active = self.dropout._rate > 0 and _is_training()
        if mask is None and _flash_enabled():
            # fused Pallas path (ops/pallas_attention.py): O(S) memory,
            # MXU-blocked QK^T/softmax/PV. Attention-prob dropout runs
            # INSIDE the kernel (counter-hash mask, regenerated in the
            # backward kernels), so training keeps the fast path.
            from ... import _random
            from ...ndarray.ndarray import apply_op
            from ...ops.pallas_attention import flash_attention

            if drop_active:
                import jax
                import jax.numpy as jnp

                rate = self.dropout._rate
                seed = jax.random.randint(_random.next_key(), (1,), 0,
                                          2 ** 31 - 1, dtype=jnp.int32)
                ctxv = apply_op(
                    lambda q_, k_, v_: flash_attention(
                        q_, k_, v_, dropout_p=rate, dropout_seed=seed),
                    q, k, v, name="flash_attention_dropout")
            else:
                ctxv = apply_op(
                    lambda q_, k_, v_: flash_attention(q_, k_, v_),
                    q, k, v, name="flash_attention")
            ctxv = ctxv.transpose((0, 2, 1, 3)).reshape((b, s, h * d))
            return self.out_proj(ctxv)
        scores = np.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
        if mask is not None:
            if mask.ndim == 2:
                bias = (1.0 - mask.astype("float32")) * -1e9
                bias = bias.reshape((b, 1, 1, s))
            else:
                bias = (1.0 - mask.astype("float32")) * -1e9
                bias = bias.reshape((b, 1) + mask.shape[1:])
            scores = scores.astype("float32") + bias
        att = npx.softmax(scores.astype("float32"), axis=-1).astype(x.dtype)
        att = self.dropout(att)
        out = np.einsum("bhqk,bhkd->bhqd", att, v)
        out = out.transpose((0, 2, 1, 3)).reshape((b, s, h * d))
        return self.out_proj(out)


class PositionwiseFFN(HybridBlock):
    """Feed-forward: Dense(hidden) -> GELU -> Dense(units)."""

    def __init__(self, units, hidden_size, dropout=0.0, dtype="float32"):
        super().__init__()
        self.ffn_1 = Dense(hidden_size, flatten=False, dtype=dtype,
                           in_units=units)
        self.ffn_2 = Dense(units, flatten=False, dtype=dtype,
                           in_units=hidden_size)
        self.dropout = Dropout(dropout)

    def forward(self, x):
        h = npx.activation(self.ffn_1(x), "gelu")
        return self.dropout(self.ffn_2(h))


class TransformerEncoderCell(HybridBlock):
    """Post-LN transformer layer (BERT convention)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 dtype="float32"):
        super().__init__()
        self.attention = MultiHeadAttention(units, num_heads, dropout,
                                            dtype)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout, dtype)
        self.layer_norm_att = LayerNorm(in_channels=units, dtype=dtype)
        self.layer_norm_ffn = LayerNorm(in_channels=units, dtype=dtype)
        self.dropout = Dropout(dropout)

    def forward(self, x, mask=None):
        att = self.dropout(self.attention(x, mask))
        x = self.layer_norm_att(x + att)
        ffn = self.ffn(x)
        return self.layer_norm_ffn(x + ffn)


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, dtype="float32"):
        super().__init__()
        self.layers = HybridSequential()
        for _ in range(num_layers):
            self.layers.add(TransformerEncoderCell(
                units, hidden_size, num_heads, dropout, dtype))

    def forward(self, x, mask=None):
        for layer in self.layers:
            x = layer(x, mask)
        return x


class BERTModel(HybridBlock):
    """Token + segment + position embeddings → encoder → (sequence,
    pooled) outputs; optional tied masked-LM decoder."""

    def __init__(self, vocab_size=30522, token_type_vocab_size=2,
                 max_length=512, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, dropout=0.1,
                 use_pooler=True, use_decoder=True, dtype="float32"):
        super().__init__()
        self._units = units
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self.word_embed = Embedding(vocab_size, units, dtype=dtype)
        self.token_type_embed = Embedding(token_type_vocab_size, units,
                                          dtype=dtype)
        self.position_embed = Embedding(max_length, units, dtype=dtype)
        self.embed_layer_norm = LayerNorm(in_channels=units, dtype=dtype)
        self.embed_dropout = Dropout(dropout)
        self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                   num_heads, dropout, dtype)
        if use_pooler:
            self.pooler = Dense(units, activation="tanh", flatten=False,
                                dtype=dtype, in_units=units)
        if use_decoder:
            # masked-LM transform; vocab projection shares word_embed's
            # weight (tied decoder, gluon-nlp convention)
            self.decoder_transform = Dense(units, activation="gelu",
                                           flatten=False, dtype=dtype,
                                           in_units=units)
            self.decoder_norm = LayerNorm(in_channels=units, dtype=dtype)
            from ..parameter import Parameter

            self.decoder_bias = Parameter("decoder_bias",
                                          shape=(vocab_size,),
                                          init="zeros", dtype=dtype)

    def _embed(self, inputs, token_types):
        b, s = inputs.shape
        pos = np.arange(s).reshape((1, s))
        pos = np.broadcast_to(pos, (b, s))
        x = (self.word_embed(inputs)
             + self.token_type_embed(token_types)
             + self.position_embed(pos))
        return self.embed_dropout(self.embed_layer_norm(x))

    def forward(self, inputs, token_types=None, valid_length=None,
                masked_positions=None):
        b, s = inputs.shape
        if token_types is None:
            token_types = np.zeros((b, s), dtype="int32")
        mask = None
        if valid_length is not None:
            mask = (np.arange(s).reshape((1, s))
                    < valid_length.reshape((-1, 1))).astype("float32")
        x = self._embed(inputs, token_types)
        seq = self.encoder(x, mask)
        outputs = [seq]
        if self._use_pooler:
            outputs.append(self.pooler(seq[:, 0]))
        if self._use_decoder and masked_positions is not None:
            picked = np.take_along_axis(
                seq, masked_positions.astype("int32")
                .reshape(masked_positions.shape + (1,)), axis=1)
            h = self.decoder_norm(self.decoder_transform(picked))
            logits = np.matmul(h, self.word_embed.weight.data_for(h).T) \
                + self.decoder_bias.data_for(h)
            outputs.append(logits)
        return outputs[0] if len(outputs) == 1 else tuple(outputs)


class BERTClassifier(HybridBlock):
    """[CLS]-pooled classification head (sentence pair tasks / NSP)."""

    def __init__(self, bert, num_classes=2, dropout=0.0):
        super().__init__()
        self.bert = bert
        self.dropout = Dropout(dropout)
        self.classifier = Dense(num_classes, flatten=False,
                                in_units=bert._units)

    def forward(self, inputs, token_types=None, valid_length=None):
        _, pooled = self.bert(inputs, token_types, valid_length)
        return self.classifier(self.dropout(pooled))


class BERTForQA(HybridBlock):
    """SQuAD-style span head: Dense(2) over sequence output giving
    start/end logits (driver config #4)."""

    def __init__(self, bert, dropout=0.0):
        super().__init__()
        self.bert = bert
        self.dropout = Dropout(dropout)
        self.span_classifier = Dense(2, flatten=False,
                                     in_units=bert._units)

    def forward(self, inputs, token_types=None, valid_length=None):
        out = self.bert(inputs, token_types, valid_length)
        seq = out[0] if isinstance(out, tuple) else out
        logits = self.span_classifier(self.dropout(seq))  # (B, S, 2)
        start = logits[:, :, 0]
        end = logits[:, :, 1]
        return start, end


_BERT_CONFIGS = {
    "bert_12_768_12": dict(num_layers=12, units=768, hidden_size=3072,
                           num_heads=12),
    "bert_24_1024_16": dict(num_layers=24, units=1024, hidden_size=4096,
                            num_heads=16),
}


def get_bert_model(model_name="bert_12_768_12", vocab_size=30522,
                   max_length=512, dropout=0.1, use_pooler=True,
                   use_decoder=True, dtype="float32", **kwargs):
    if model_name not in _BERT_CONFIGS:
        raise ValueError(
            f"unknown BERT config {model_name}; "
            f"choose from {sorted(_BERT_CONFIGS)}")
    cfg = dict(_BERT_CONFIGS[model_name])
    cfg.update(kwargs)
    return BERTModel(vocab_size=vocab_size, max_length=max_length,
                     dropout=dropout, use_pooler=use_pooler,
                     use_decoder=use_decoder, dtype=dtype, **cfg)


def bert_12_768_12(**kwargs):
    """BERT-base."""
    return get_bert_model("bert_12_768_12", **kwargs)


def bert_24_1024_16(**kwargs):
    """BERT-large."""
    return get_bert_model("bert_24_1024_16", **kwargs)
