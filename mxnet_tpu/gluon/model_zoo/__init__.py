"""Model zoo (reference: python/mxnet/gluon/model_zoo/)."""
from . import bert, model_store, vision  # noqa: F401
from .bert import bert_12_768_12, bert_24_1024_16, get_bert_model  # noqa: F401
from .vision import get_model  # noqa: F401
