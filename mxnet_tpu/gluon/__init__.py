"""Gluon — imperative model authoring with optional compilation.

Reference: python/mxnet/gluon/ (27k LoC). Subpackages: nn (layers), rnn,
loss, metric, data, model_zoo, contrib; core classes Block/HybridBlock,
Parameter, Trainer.
"""
from . import contrib, data, loss, metric, model_zoo, nn, probability, rnn, utils  # noqa: F401
from .block import Block, HybridBlock, SymbolBlock  # noqa: F401
from .parameter import Constant, Parameter  # noqa: F401
from .train_step import TrainStep  # noqa: F401
from .trainer import Trainer  # noqa: F401
from ..base import DeferredInitializationError  # noqa: F401


class ParameterDict(dict):
    """Compat shim for 1.x-style param dicts (removed in reference 2.x)."""
