"""Evaluation metrics (reference: python/mxnet/gluon/metric.py, 1867 LoC)."""
from __future__ import annotations

import numpy as _np

from ..base import registry
from ..ndarray.ndarray import NDArray

_REG = registry("metric")

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "Fbeta",
           "BinaryAccuracy", "MCC", "PCC", "MAE", "MSE", "RMSE",
           "CrossEntropy", "Perplexity", "PearsonCorrelation",
           "MeanCosineSimilarity", "MeanPairwiseDistance", "Loss",
           "Torch", "CompositeEvalMetric", "CustomMetric", "create", "np"]


def _fbeta_score(tp, fp, fn, beta):
    """Shared F-score kernel: F1 is the beta=1 case."""
    prec = tp / max(tp + fp, 1e-12)
    rec = tp / max(tp + fn, 1e-12)
    b2 = beta ** 2
    return (1 + b2) * prec * rec / max(b2 * prec + rec, 1e-12)


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        out = CompositeEvalMetric()
        for m in metric:
            out.add(create(m, *args, **kwargs))
        return out
    return _REG.create(metric, *args, **kwargs)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


def _register(klass):
    _REG.register(klass)
    return klass


@_register
class Accuracy(EvalMetric):
    def __init__(self, axis=-1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred)
            if pred.ndim > 1 and pred.shape != label.shape:
                # class-probability rows argmax to labels whenever the
                # shapes differ — labels may arrive 2-D from custom
                # iterators (reference Accuracy.update, test_metric.py:71)
                pred = pred.argmax(self.axis)
            pred = pred.astype(_np.int64).reshape(-1)
            label = label.astype(_np.int64).reshape(-1)
            if len(pred) != len(label):
                # reference check_label_shapes: loud, never broadcast
                raise ValueError(
                    f"Accuracy: {len(pred)} predictions vs "
                    f"{len(label)} labels")
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@_register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label).astype(_np.int64)
            pred = _to_np(pred)
            topk = _np.argsort(-pred, axis=-1)[..., : self.top_k]
            hit = (topk == label[..., None]).any(axis=-1)
            self.sum_metric += float(hit.sum())
            self.num_inst += hit.size


@_register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", threshold=0.5, **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.threshold = threshold
        self.reset_stats()

    _beta = 1.0  # Fbeta overrides; one macro/micro get() serves both

    def reset_stats(self):
        self._tp = self._fp = self._fn = 0.0
        self._batch_counts = []  # per-update (tp, fp, fn) for 'macro'

    def reset(self):
        super().reset()
        if hasattr(self, "_tp"):
            self.reset_stats()

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        up_tp = up_fp = up_fn = 0.0
        for label, pred in zip(labels, preds):
            label = _to_np(label).reshape(-1).astype(_np.int64)
            pred = _to_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1).reshape(-1)
            else:
                pred = (pred.reshape(-1) > self.threshold).astype(_np.int64)
            up_tp += float(((pred == 1) & (label == 1)).sum())
            up_fp += float(((pred == 1) & (label == 0)).sum())
            up_fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)
        self._tp += up_tp
        self._fp += up_fp
        self._fn += up_fn
        if self.average == "macro":
            # macro: mean of per-UPDATE F scores (reference 'macro'
            # averages across batches; 'micro' pools the counts)
            self._batch_counts.append((up_tp, up_fp, up_fn))

    def get(self):
        if not self.num_inst:
            return self.name, float("nan")
        if self.average == "macro" and self._batch_counts:
            return self.name, float(_np.mean(
                [_fbeta_score(tp, fp, fn, self._beta)
                 for tp, fp, fn in self._batch_counts]))
        return self.name, _fbeta_score(self._tp, self._fp, self._fn,
                                       self._beta)


@_register
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self._tp = self._tn = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        self._tp = self._tn = self._fp = self._fn = 0.0

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label).reshape(-1).astype(_np.int64)
            pred = _to_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1).reshape(-1)
            else:
                pred = (pred.reshape(-1) > 0.5).astype(_np.int64)
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._tn += float(((pred == 0) & (label == 0)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)

    def get(self):
        tp, tn, fp, fn = self._tp, self._tn, self._fp, self._fn
        denom = ((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)) ** 0.5
        mcc = (tp * tn - fp * fn) / denom if denom else 0.0
        return self.name, mcc if self.num_inst else float("nan")


@_register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred).reshape(label.shape)
            self.sum_metric += float(_np.abs(label - pred).mean()) * len(label)
            self.num_inst += len(label)


@_register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred).reshape(label.shape)
            self.sum_metric += float(((label - pred) ** 2).mean()) * len(label)
            self.num_inst += len(label)


@_register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name=name, **kwargs)

    def get(self):
        name, value = super().get()
        return name, value ** 0.5 if value == value else value


@_register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label).reshape(-1).astype(_np.int64)
            pred = _to_np(pred)
            prob = pred[_np.arange(len(label)), label]
            self.sum_metric += float(-_np.log(prob + self.eps).sum())
            self.num_inst += len(label)


@_register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(_np.exp(self.sum_metric / self.num_inst))


@_register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)
        self._labels = []
        self._preds = []

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            self._labels.append(_to_np(label).reshape(-1))
            self._preds.append(_to_np(pred).reshape(-1))
            self.num_inst += len(self._labels[-1])

    def get(self):
        if not self._labels:
            return self.name, float("nan")
        x = _np.concatenate(self._labels)
        y = _np.concatenate(self._preds)
        return self.name, float(_np.corrcoef(x, y)[0, 1])


@_register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for pred in preds:
            p = _to_np(pred)
            self.sum_metric += float(p.sum())
            self.num_inst += p.size


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):  # noqa: ARG002
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            out = self._feval(_to_np(label), _to_np(pred))
            if isinstance(out, tuple):
                s, n = out
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += out
                self.num_inst += 1


np = _np  # parity: reference metric module exposes numpy as .np
_REG.register(Accuracy, "acc")
_REG.register(CrossEntropy, "ce")
_REG.register(TopKAccuracy, "top_k_acc")


@_register
class Fbeta(F1):
    """Fbeta = (1+β²)·precision·recall / (β²·precision + recall)
    (reference: metric.py:816)."""

    def __init__(self, name="fbeta", beta=1.0, **kwargs):
        super().__init__(name, **kwargs)
        self.beta = beta
        self._beta = beta  # F1.get() computes macro/micro with this


@_register
class BinaryAccuracy(EvalMetric):
    """Elementwise accuracy of binary/multilabel predictions against a
    decision threshold (reference: metric.py:877)."""

    def __init__(self, name="binary_accuracy", threshold=0.5, **kwargs):
        super().__init__(name, **kwargs)
        self.threshold = threshold

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label).reshape(-1)
            pred = (_to_np(pred).reshape(-1) > self.threshold)
            hit = (pred == (label > 0.5))
            self.sum_metric += float(hit.sum())
            self.num_inst += hit.size


@_register
class MeanCosineSimilarity(EvalMetric):
    """Mean per-sample cosine similarity along the last axis
    (reference: metric.py:1260)."""

    def __init__(self, name="cos_sim", eps=1e-8, **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred)
            if label.ndim == 1:
                label, pred = label[None], pred[None]
            num = (label * pred).sum(-1)
            den = (_np.linalg.norm(label, axis=-1)
                   * _np.linalg.norm(pred, axis=-1))
            sim = num / _np.maximum(den, self.eps)
            self.sum_metric += float(sim.sum())
            self.num_inst += sim.size


@_register
class MeanPairwiseDistance(EvalMetric):
    """Mean per-sample L_p distance along the last axis
    (reference: metric.py:1199)."""

    def __init__(self, name="mpd", p=2, **kwargs):
        super().__init__(name, **kwargs)
        self.p = p

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred)
            if label.ndim == 1:
                label, pred = label[None], pred[None]
            dist = (_np.abs(pred - label) ** self.p).sum(-1) ** (1 / self.p)
            self.sum_metric += float(dist.sum())
            self.num_inst += dist.size


@_register
class PCC(EvalMetric):
    """Multiclass Pearson correlation from a running confusion matrix —
    the discrete MCC generalization (reference: metric.py:1595). Equals
    MCC for binary problems."""

    def __init__(self, name="pcc", **kwargs):
        self._cm = _np.zeros((0, 0), dtype=_np.float64)
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self._cm = _np.zeros((0, 0), dtype=_np.float64)

    def _grow(self, k):
        if k > self._cm.shape[0]:
            cm = _np.zeros((k, k), dtype=_np.float64)
            n = self._cm.shape[0]
            cm[:n, :n] = self._cm
            self._cm = cm

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_np(label).reshape(-1).astype(_np.int64)
            pred = _to_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1).reshape(-1).astype(_np.int64)
            else:
                # 1-D probabilities: threshold like F1/MCC so binary
                # PCC == MCC holds for sigmoid outputs too
                pred = (pred.reshape(-1) > 0.5).astype(_np.int64)
            k = int(max(label.max(initial=0), pred.max(initial=0))) + 1
            self._grow(k)
            _np.add.at(self._cm, (pred, label), 1)
            self.num_inst += len(label)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        c = self._cm
        n = c.sum()
        x = c.sum(axis=1)  # predicted counts
        y = c.sum(axis=0)  # true counts
        cov_xy = n * _np.trace(c) - (x * y).sum()
        cov_xx = n * n - (x * x).sum()
        cov_yy = n * n - (y * y).sum()
        den = _np.sqrt(cov_xx * cov_yy)
        return self.name, float(cov_xy / den) if den else float("nan")


Torch = Loss  # reference keeps the legacy Torch criterion name as Loss
_REG.register(Loss, "torch")
