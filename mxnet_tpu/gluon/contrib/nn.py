"""Contrib layers beyond the reference's surface.

`MoEDense` — Mixture-of-Experts FFN (GShard-style top-k routing over
`parallel/moe.py`). The reference has no MoE; this layer plus
`parallel.moe_ffn_sharded` gives expert parallelism as a first-class
capability (shard the expert dimension over an 'ep' mesh axis).
"""
from __future__ import annotations

from ...ndarray.ndarray import apply_op
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["MoEDense"]


class MoEDense(HybridBlock):
    """MoE feed-forward: route each token to top_k of num_experts FFNs.

    Input (..., in_units) -> (output (..., in_units), aux_loss). The
    auxiliary load-balancing loss should be added to the training loss
    (scaled by ~1e-2), per the Switch-Transformer recipe.
    """

    def __init__(self, in_units, hidden_units, num_experts, top_k=2,
                 capacity_factor=1.25, weight_initializer=None):
        super().__init__()
        self._E = int(num_experts)
        self._top_k = int(top_k)
        self._cf = float(capacity_factor)
        self.router = Parameter("router", shape=(in_units, num_experts),
                                init=weight_initializer)
        self.wi = Parameter("wi",
                            shape=(num_experts, in_units, hidden_units),
                            init=weight_initializer)
        self.wo = Parameter("wo",
                            shape=(num_experts, hidden_units, in_units),
                            init=weight_initializer)

    def forward(self, x):
        from ...parallel import moe as _moe

        router = self.router.data_for(x)
        wi = self.wi.data_for(x)
        wo = self.wo.data_for(x)

        def pure(xv, r, a, b):
            shape = xv.shape
            tokens = xv.reshape(-1, shape[-1])
            out, aux = _moe.moe_ffn(
                {"router": r, "wi": a, "wo": b}, tokens,
                capacity_factor=self._cf, top_k=self._top_k)
            return out.reshape(shape), aux

        return apply_op(pure, x, router, wi, wo, name="moe_dense")

    def __repr__(self):
        return (f"MoEDense(experts={self._E}, top_k={self._top_k}, "
                f"capacity_factor={self._cf})")
