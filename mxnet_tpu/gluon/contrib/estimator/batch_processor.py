"""Batch processor — per-minibatch hooks for Estimator (reference:
gluon/contrib/estimator/batch_processor.py:28). Subclass and override
`fit_batch` / `evaluate_batch` to customize the inner loop (multi-output
models, custom losses, adversarial steps) without rewriting `fit`."""
from __future__ import annotations

from .... import autograd

__all__ = ["BatchProcessor"]


class BatchProcessor:
    def _get_data_and_label(self, batch, device, batch_axis=0):  # noqa: ARG002
        data, label = batch[0], batch[1]
        return data.as_in_ctx(device), label.as_in_ctx(device)

    def evaluate_batch(self, estimator, val_batch, batch_axis=0):
        """One validation step: returns (data, label, pred, loss) using
        the estimator's validation net/loss when configured."""
        data, label = self._get_data_and_label(
            val_batch, estimator.device, batch_axis)
        net = getattr(estimator, "val_net", estimator.net)
        lossfn = getattr(estimator, "val_loss", estimator.loss)
        pred = net(data)
        loss = lossfn(pred, label)
        return data, label, pred, loss

    def fit_batch(self, estimator, train_batch, batch_axis=0):
        """One training step: forward under record, backward, and return
        (data, label, pred, loss); GradientUpdateHandler runs trainer.step sized from the per-sample loss vector (0-d losses step with 1)."""
        data, label = self._get_data_and_label(
            train_batch, estimator.device, batch_axis)
        with autograd.record():
            pred = estimator.net(data)
            loss = estimator.loss(pred, label)
        loss.backward()
        return data, label, pred, loss
