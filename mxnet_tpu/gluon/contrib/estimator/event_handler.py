"""Estimator event handlers (reference: estimator/event_handler.py:37-336)."""
from __future__ import annotations

import logging
import os
import time

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler", "LoggingHandler",
           "ValidationHandler", "CheckpointHandler", "EarlyStoppingHandler",
           "GradientUpdateHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop at max_epoch/max_batch (reference: StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    """Update train metrics per batch (reference: MetricHandler:122)."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            from ...metric import Loss as LossMetric

            if isinstance(m, LossMetric):
                m.update(0, loss)
            else:
                m.update(label, pred)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Periodic logging (reference: LoggingHandler:226)."""

    def __init__(self, log_interval="epoch", metrics=None, priority=1000):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.priority = priority
        self.batch_index = 0
        self.current_epoch = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training done in %.1fs",
                         time.time() - self.train_start)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0

    def epoch_end(self, estimator, *args, **kwargs):
        msg = f"[Epoch {self.current_epoch}] done in " \
              f"{time.time() - self.epoch_start:.1f}s"
        for m in self.metrics:
            name, value = m.get()
            msg += f" {name}={value:.4f}"
        self.logger.info(msg)
        self.current_epoch += 1

    def batch_end(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int):
            self.batch_index += 1
            if self.batch_index % self.log_interval == 0:
                msg = f"[Epoch {self.current_epoch}][Batch {self.batch_index}]"
                for m in self.metrics:
                    name, value = m.get()
                    msg += f" {name}={value:.4f}"
                self.logger.info(msg)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation periodically (reference: ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_epoch = 0
        self.current_batch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Periodic / best-only checkpointing (reference: CheckpointHandler:336).

    Two backends:
      * legacy (default): `net.save_parameters` + `trainer.save_states`
        file pairs with simple rotation — the reference's behavior;
      * `manager=`: a `mx.checkpoint.CheckpointManager` — every periodic
        save becomes an atomic manifest checkpoint (params + optimizer +
        RNG + epoch/batch cursor in user_state), retention moves to the
        manager, and `resume_from_checkpoint=True` actually resumes:
        train_begin restores the latest committed checkpoint and fast-
        forwards the epoch/batch counters (a cold directory is not an
        error — training just starts fresh).
    """

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False, manager=None):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.verbose = verbose
        self.resume_from_checkpoint = resume_from_checkpoint
        self.manager = manager
        self.current_epoch = 0
        self.current_batch = 0
        self.best = None
        if mode == "min" or (mode == "auto" and monitor is not None
                             and "loss" in monitor.get()[0]):
            self.monitor_op = lambda new, best: new < best
        else:
            self.monitor_op = lambda new, best: new > best
        self.saved = []
        os.makedirs(model_dir, exist_ok=True)

    def train_begin(self, estimator, *args, **kwargs):
        if self.manager is None or not self.resume_from_checkpoint:
            return
        from ....checkpoint import CheckpointNotFound

        self.manager.bind(estimator.trainer)
        try:
            result = self.manager.restore()
        except CheckpointNotFound:
            return  # cold start: nothing committed yet
        cursor = result.user_state or {}
        self.current_epoch = int(cursor.get("epoch", self.current_epoch))
        self.current_batch = int(cursor.get("batch", self.current_batch))
        logging.getLogger("mxnet_tpu.estimator").info(
            "Resumed from checkpoint step %d (epoch %d, batch %d)",
            result.step, self.current_epoch, self.current_batch)

    def _save(self, estimator, tag, rotate=True):
        if self.manager is not None:
            # manager path: one atomic checkpoint carries params + states
            # + RNG + cursor; retention/rotation is the manager's job.
            # 'best' still goes through the legacy file pair below so it
            # can never be rotated away by keep_last.
            if rotate:
                self.manager.bind(estimator.trainer)
                self.manager.save(
                    step=self.current_batch,
                    user_state={"epoch": self.current_epoch,
                                "batch": self.current_batch, "tag": tag})
                return
        path = os.path.join(self.model_dir,
                            f"{self.model_prefix}-{tag}.params")
        estimator.net.save_parameters(path)
        if rotate:
            # rotation applies only to periodic checkpoints; the 'best'
            # checkpoint overwrites in place and is never rotated away
            self.saved.append(path)
            while len(self.saved) > self.max_checkpoints:
                old = self.saved.pop(0)
                from ...._checkpoint_io import wait_for_path

                wait_for_path(old)  # the async write may still be queued
                if os.path.exists(old):
                    os.remove(old)
        if estimator.trainer is not None:
            estimator.trainer.save_states(path + ".states")

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator, f"batch{self.current_batch}")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            if self.save_best and self.monitor is not None:
                _, value = self.monitor.get()
                if self.best is None or self.monitor_op(value, self.best):
                    self.best = value
                    self._save(estimator, "best", rotate=False)
            else:
                self._save(estimator, f"epoch{self.current_epoch}")


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when a metric stops improving (reference: EarlyStoppingHandler)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        if mode == "min" or (mode == "auto" and "loss" in monitor.get()[0]):
            self.monitor_op = lambda new, best: new < best - min_delta
            self.best = float("inf")
        else:
            self.monitor_op = lambda new, best: new > best + min_delta
            self.best = -float("inf")
        if baseline is not None:
            self.best = baseline

    def epoch_end(self, estimator, *args, **kwargs):
        _, value = self.monitor.get()
        if value == value and self.monitor_op(value, self.best):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                self.stop_training = True
        self.current_epoch += 1
        return self.stop_training

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch > 0:
            logging.getLogger("mxnet_tpu.estimator").info(
                "Early stop at epoch %d", self.stopped_epoch)


class GradientUpdateHandler(BatchEnd):
    """Applies the optimizer step at the end of each batch (reference:
    event_handler.py:722). Runs FIRST among batch_end handlers
    (priority -2000) so metric/logging handlers see updated state.
    Batch size comes from the per-sample loss vector like the
    reference; a pre-reduced 0-d loss steps with batch_size=1 (its
    gradients already carry the 1/batch scale)."""

    def __init__(self, priority=-2000):
        self.priority = priority

    def batch_end(self, estimator, *args, **kwargs):
        loss = kwargs.get("loss")
        losses = loss if isinstance(loss, (list, tuple)) else [loss]
        # per-sample loss vectors step with their row count (grads get
        # rescaled by 1/batch); an already-reduced 0-d loss steps with 1
        # (its grads are already mean-scaled)
        batch_size = sum(l.shape[0] if getattr(l, "ndim", 0) else 1
                         for l in losses)
        estimator.trainer.step(batch_size)
