"""Estimator — high-level fit loop (reference: estimator/estimator.py)."""
from __future__ import annotations

from .... import autograd
from ....device import current_device
from ...metric import Accuracy, EvalMetric, Loss as LossMetric
from ...trainer import Trainer
from .event_handler import (
    BatchBegin,
    BatchEnd,
    EpochBegin,
    EpochEnd,
    GradientUpdateHandler,
    LoggingHandler,
    MetricHandler,
    StoppingHandler,
    TrainBegin,
    TrainEnd,
)

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 initializer=None, trainer=None, device=None, context=None,
                 evaluation_loss=None, val_net=None, val_loss=None,
                 batch_processor=None):
        from .batch_processor import BatchProcessor

        self.batch_processor = batch_processor or BatchProcessor()
        self.net = net
        # validation may use a different head / loss sharing parameters
        # (reference: estimator.py val_net/val_loss/evaluation_loss)
        self.val_net = val_net if val_net is not None else net
        self.val_loss = (val_loss if val_loss is not None
                         else evaluation_loss if evaluation_loss is not None
                         else loss)
        self.loss = loss
        self.device = device or context or current_device()
        if train_metrics is None:
            train_metrics = [Accuracy()]
        elif isinstance(train_metrics, EvalMetric):
            train_metrics = [train_metrics]
        self.train_metrics = list(train_metrics) + [LossMetric("train_loss")]
        if val_metrics is None:
            val_metrics = [Accuracy(name="val_accuracy")]
        elif isinstance(val_metrics, EvalMetric):
            val_metrics = [val_metrics]
        self.val_metrics = list(val_metrics)
        if initializer is not None:
            net.initialize(init=initializer, device=self.device)
        else:
            try:
                for p in net.collect_params().values():
                    p._check_initialized()
            except Exception:
                net.initialize(device=self.device)
        self.trainer = trainer or Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.001})

    def _batch_fn(self, batch):
        data, label = batch[0], batch[1]
        return (data.as_in_ctx(self.device), label.as_in_ctx(self.device))

    @staticmethod
    def _check_data(name, d, batch_fn):
        """Reference estimator.py _check_data: only gluon DataLoader is
        accepted without a custom batch_fn — raw arrays or legacy
        DataIters would mis-unpack into (data, label)."""
        from ...data.dataloader import DataLoader

        if batch_fn is None and d is not None \
                and not isinstance(d, DataLoader):
            raise ValueError(
                f"Estimator only supports gluon DataLoader for {name} "
                f"(got {type(d).__name__}); pass batch_fn to adapt "
                f"other iterators")

    def evaluate(self, val_data, batch_fn=None):
        """Run validation using the dedicated val metrics — train metric
        objects are left untouched (reference keeps the two sets separate)."""
        self._check_data("val_data", val_data, batch_fn)
        for m in self.val_metrics:
            m.reset()
        for batch in val_data:
            if batch_fn is not None:
                data, label = batch_fn(batch)
                pred = self.val_net(data)
            else:
                _, label, pred, _ = self.batch_processor.evaluate_batch(
                    self, batch)
            for m in self.val_metrics:
                m.update(label, pred)
        return {m.get()[0]: m.get()[1] for m in self.val_metrics}

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_fn=None):
        if (epochs is None) == (batches is None):
            raise ValueError(
                "fit() needs exactly one of epochs / batches "
                "(reference: estimator.py fit)")
        self._check_data("train_data", train_data, batch_fn)
        self._check_data("val_data", val_data, batch_fn)
        handlers = list(event_handlers or [])
        stopper = StoppingHandler(epochs, batches)
        handlers.append(stopper)
        if not any(isinstance(h, GradientUpdateHandler) for h in handlers):
            handlers.append(GradientUpdateHandler())
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(metrics=self.train_metrics))
        handlers.sort(key=lambda h: getattr(h, "priority", 0))

        def fire(kind, *args, **kwargs):
            stop = False
            for h in handlers:
                if isinstance(h, kind_map[kind]):
                    if getattr(h, kind)(self, *args, **kwargs):
                        stop = True
            return stop

        kind_map = {
            "train_begin": TrainBegin, "train_end": TrainEnd,
            "epoch_begin": EpochBegin, "epoch_end": EpochEnd,
            "batch_begin": BatchBegin, "batch_end": BatchEnd,
        }

        fire("train_begin")
        while not stopper.stop_training:
            fire("epoch_begin")
            for batch in train_data:
                fire("batch_begin")
                if batch_fn is not None:
                    data, label = batch_fn(batch)
                    with autograd.record():
                        pred = self.net(data)
                        loss = self.loss(pred, label)
                    loss.backward()
                else:
                    data, label, pred, loss = \
                        self.batch_processor.fit_batch(self, batch)
                if fire("batch_end", pred=pred, label=label, loss=loss):
                    break
            if val_data is not None:
                self.evaluate(val_data, batch_fn)
            if fire("epoch_end"):
                break
        fire("train_end")
        return self
