"""Estimator fit loop (reference: gluon/contrib/estimator/)."""
from .batch_processor import BatchProcessor  # noqa: F401
from .estimator import Estimator  # noqa: F401
from .event_handler import (  # noqa: F401
    BatchBegin,
    BatchEnd,
    CheckpointHandler,
    EarlyStoppingHandler,
    EpochBegin,
    EpochEnd,
    GradientUpdateHandler,
    LoggingHandler,
    MetricHandler,
    StoppingHandler,
    TrainBegin,
    TrainEnd,
    ValidationHandler,
)
