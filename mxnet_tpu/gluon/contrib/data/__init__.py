"""Contrib data utilities (reference: gluon/contrib/data/)."""
from . import vision  # noqa: F401
