"""Joint image+bbox transform blocks (reference:
gluon/contrib/data/vision/transforms/bbox/bbox.py). Each takes
(image HWC, bbox (N, 4+)) and returns the transformed pair — the
detection-pipeline analogs of the classification transforms."""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

from mxnet_tpu import numpy as _mxnp
from mxnet_tpu.gluon.block import Block
from mxnet_tpu.image.image import imresize

from . import utils

__all__ = ["ImageBboxRandomFlipLeftRight", "ImageBboxCrop",
           "ImageBboxRandomCropWithConstraints", "ImageBboxRandomExpand",
           "ImageBboxResize"]


def _img_np(img):
    return img.asnumpy() if hasattr(img, "asnumpy") else _np.asarray(img)


class ImageBboxRandomFlipLeftRight(Block):
    """Flip image + boxes horizontally with probability p (reference:
    bbox.py:34)."""

    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def forward(self, img, bbox):
        if _pyrandom.random() < self.p:
            arr = _img_np(img)[:, ::-1]
            bbox = utils.bbox_flip(bbox, (arr.shape[1], arr.shape[0]),
                                   flip_x=True)
            return _mxnp.array(arr.copy()), _mxnp.array(bbox)
        return (img if hasattr(img, "asnumpy") else _mxnp.array(img),
                _mxnp.array(utils._as_np(bbox)))


class ImageBboxCrop(Block):
    """Crop a fixed (x, y, w, h) region from image + boxes (reference:
    bbox.py:90)."""

    def __init__(self, crop_box, allow_outside_center=False):
        super().__init__()
        self._crop = crop_box
        self._allow = allow_outside_center

    def forward(self, img, bbox):
        x, y, w, h = self._crop
        arr = _img_np(img)[y:y + h, x:x + w]
        new_bbox = utils.bbox_crop(bbox, self._crop, self._allow)
        return _mxnp.array(arr.copy()), _mxnp.array(new_bbox)


class ImageBboxRandomCropWithConstraints(Block):
    """SSD random crop with min-IoU constraints (reference: bbox.py:146)."""

    def __init__(self, p=0.5, min_scale=0.3, max_scale=1,
                 max_aspect_ratio=2, constraints=None, max_trial=50):
        super().__init__()
        self.p = p
        self._kwargs = dict(min_scale=min_scale, max_scale=max_scale,
                            max_aspect_ratio=max_aspect_ratio,
                            constraints=constraints, max_trial=max_trial)

    def forward(self, img, bbox):
        if _pyrandom.random() > self.p:
            return (img if hasattr(img, "asnumpy") else _mxnp.array(img),
                    _mxnp.array(utils._as_np(bbox)))
        arr = _img_np(img)
        h, w = arr.shape[:2]
        new_bbox, crop = utils.bbox_random_crop_with_constraints(
            bbox, (w, h), **self._kwargs)
        x, y, cw, ch = (int(v) for v in crop)
        return (_mxnp.array(arr[y:y + ch, x:x + cw].copy()),
                _mxnp.array(new_bbox))


class ImageBboxRandomExpand(Block):
    """Place the image on a larger canvas (mean-filled) and translate the
    boxes — the SSD zoom-out augmentation (reference: bbox.py:216)."""

    def __init__(self, p=0.5, max_ratio=4, fill=0, keep_ratio=True):
        super().__init__()
        self.p = p
        self._max_ratio = max_ratio
        self._fill = fill
        self._keep_ratio = keep_ratio

    def forward(self, img, bbox):
        if self._max_ratio <= 1 or _pyrandom.random() > self.p:
            return (img if hasattr(img, "asnumpy") else _mxnp.array(img),
                    _mxnp.array(utils._as_np(bbox)))
        arr = _img_np(img)
        h, w, c = arr.shape
        rx = _pyrandom.uniform(1, self._max_ratio)
        ry = rx if self._keep_ratio else _pyrandom.uniform(
            1, self._max_ratio)
        oh, ow = int(h * ry), int(w * rx)
        off_y = _pyrandom.randrange(oh - h + 1)
        off_x = _pyrandom.randrange(ow - w + 1)
        canvas = _np.full((oh, ow, c), self._fill, arr.dtype)
        canvas[off_y:off_y + h, off_x:off_x + w] = arr
        new_bbox = utils.bbox_translate(bbox, off_x, off_y)
        return _mxnp.array(canvas), _mxnp.array(new_bbox)


class ImageBboxResize(Block):
    """Resize the image to (width, height) and rescale boxes (reference:
    bbox.py:297)."""

    def __init__(self, width, height, interp=1):
        super().__init__()
        self._size = (int(width), int(height))
        self._interp = interp

    def forward(self, img, bbox):
        arr = _img_np(img)
        h, w = arr.shape[:2]
        resized = imresize(
            img if hasattr(img, "asnumpy") else _mxnp.array(img),
            self._size[0], self._size[1], interp=self._interp)
        new_bbox = utils.bbox_resize(bbox, (w, h), self._size)
        return resized, _mxnp.array(new_bbox)
