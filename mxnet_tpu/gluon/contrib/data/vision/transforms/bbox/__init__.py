"""Image+bbox joint transforms (reference: .../transforms/bbox/)."""
from . import utils  # noqa: F401
from .bbox import (  # noqa: F401
    ImageBboxCrop,
    ImageBboxRandomCropWithConstraints,
    ImageBboxRandomExpand,
    ImageBboxRandomFlipLeftRight,
    ImageBboxResize,
)
