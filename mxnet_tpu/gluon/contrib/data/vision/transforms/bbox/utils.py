"""Bounding-box geometry helpers (reference:
gluon/contrib/data/vision/transforms/bbox/utils.py). Boxes are numpy
(N, 4+) xyxy unless stated; extra columns (class ids) pass through."""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

__all__ = ["bbox_crop", "bbox_flip", "bbox_resize", "bbox_translate",
           "bbox_iou", "bbox_xywh_to_xyxy", "bbox_xyxy_to_xywh",
           "bbox_clip_xyxy", "bbox_random_crop_with_constraints"]


def _as_np(bbox):
    arr = bbox.asnumpy() if hasattr(bbox, "asnumpy") else _np.asarray(bbox)
    if arr.ndim != 2 or arr.shape[1] < 4:
        raise ValueError(
            f"bbox must be (N, >=4), got {arr.shape}")
    return _np.array(arr, dtype=_np.float64, copy=True)


def bbox_crop(bbox, crop_box=None, allow_outside_center=True):
    """Crop boxes to `crop_box` (x, y, w, h); boxes fully outside (or with
    center outside when disallowed) are dropped (reference: utils.py:30)."""
    bbox = _as_np(bbox)
    if crop_box is None:
        return bbox
    if sum(x is None for x in crop_box) == 4:
        return bbox
    l, t, w, h = (0 if v is None else float(v) for v in crop_box)
    r = l + (w if w else _np.inf)
    b = t + (h if h else _np.inf)
    out = bbox.copy()
    out[:, 0] = _np.clip(bbox[:, 0], l, r) - l
    out[:, 1] = _np.clip(bbox[:, 1], t, b) - t
    out[:, 2] = _np.clip(bbox[:, 2], l, r) - l
    out[:, 3] = _np.clip(bbox[:, 3], t, b) - t
    if allow_outside_center:
        mask = _np.ones(len(out), bool)
    else:
        cx = (bbox[:, 0] + bbox[:, 2]) / 2
        cy = (bbox[:, 1] + bbox[:, 3]) / 2
        mask = (cx >= l) & (cx <= r) & (cy >= t) & (cy <= b)
    mask &= (out[:, 2] > out[:, 0]) & (out[:, 3] > out[:, 1])
    return out[mask]


def bbox_flip(bbox, size, flip_x=False, flip_y=False):
    """Flip boxes inside a (width, height) canvas (reference:
    utils.py:85)."""
    bbox = _as_np(bbox)
    w, h = size
    if flip_x:
        x1 = w - bbox[:, 2]
        x2 = w - bbox[:, 0]
        bbox[:, 0], bbox[:, 2] = x1, x2
    if flip_y:
        y1 = h - bbox[:, 3]
        y2 = h - bbox[:, 1]
        bbox[:, 1], bbox[:, 3] = y1, y2
    return bbox


def bbox_resize(bbox, in_size, out_size):
    """Rescale boxes from in_size (w, h) to out_size (reference:
    utils.py:124)."""
    bbox = _as_np(bbox)
    sx = out_size[0] / in_size[0]
    sy = out_size[1] / in_size[1]
    bbox[:, 0] *= sx
    bbox[:, 2] *= sx
    bbox[:, 1] *= sy
    bbox[:, 3] *= sy
    return bbox


def bbox_translate(bbox, x_offset=0, y_offset=0):
    """Shift boxes (reference: utils.py:159)."""
    bbox = _as_np(bbox)
    bbox[:, 0] += x_offset
    bbox[:, 2] += x_offset
    bbox[:, 1] += y_offset
    bbox[:, 3] += y_offset
    return bbox


def bbox_iou(bbox_a, bbox_b, offset=0):
    """Pairwise IoU matrix (reference: utils.py:185)."""
    a = _as_np(bbox_a)
    b = _as_np(bbox_b)
    tl = _np.maximum(a[:, None, :2], b[None, :, :2])
    br = _np.minimum(a[:, None, 2:4], b[None, :, 2:4])
    inter = _np.prod(_np.clip(br - tl + offset, 0, None), axis=2) * \
        (tl < br).all(axis=2)
    area_a = _np.prod(a[:, 2:4] - a[:, :2] + offset, axis=1)
    area_b = _np.prod(b[:, 2:4] - b[:, :2] + offset, axis=1)
    union = area_a[:, None] + area_b[None, :] - inter
    return _np.where(union > 0, inter / union, 0.0)


def bbox_xywh_to_xyxy(xywh):
    """(x, y, w, h) -> (x1, y1, x2, y2); tuple in, tuple out
    (reference: utils.py:218)."""
    if isinstance(xywh, (tuple, list)):
        if len(xywh) != 4:
            raise IndexError(f"expected length 4, got {len(xywh)}")
        x, y, w, h = xywh
        return (x, y, x + _np.maximum(0, w - 1),
                y + _np.maximum(0, h - 1))
    arr = _np.array(xywh, dtype=_np.float64, copy=True)
    arr[:, 2] = arr[:, 0] + _np.maximum(0, arr[:, 2] - 1)
    arr[:, 3] = arr[:, 1] + _np.maximum(0, arr[:, 3] - 1)
    return arr


def bbox_xyxy_to_xywh(xyxy):
    """(x1, y1, x2, y2) -> (x, y, w, h) (reference: utils.py:252)."""
    if isinstance(xyxy, (tuple, list)):
        if len(xyxy) != 4:
            raise IndexError(f"expected length 4, got {len(xyxy)}")
        x1, y1, x2, y2 = xyxy
        return (x1, y1, x2 - x1 + 1, y2 - y1 + 1)
    arr = _np.array(xyxy, dtype=_np.float64, copy=True)
    arr[:, 2] = arr[:, 2] - arr[:, 0] + 1
    arr[:, 3] = arr[:, 3] - arr[:, 1] + 1
    return arr


def bbox_clip_xyxy(xyxy, width, height):
    """Clip boxes to image bounds (reference: utils.py:286)."""
    if isinstance(xyxy, (tuple, list)):
        if len(xyxy) != 4:
            raise IndexError(f"expected length 4, got {len(xyxy)}")
        x1 = _np.minimum(width - 1, _np.maximum(0, xyxy[0]))
        y1 = _np.minimum(height - 1, _np.maximum(0, xyxy[1]))
        x2 = _np.minimum(width - 1, _np.maximum(0, xyxy[2]))
        y2 = _np.minimum(height - 1, _np.maximum(0, xyxy[3]))
        return (x1, y1, x2, y2)
    arr = _np.array(xyxy, dtype=_np.float64, copy=True)
    arr[:, 0] = _np.clip(arr[:, 0], 0, width - 1)
    arr[:, 1] = _np.clip(arr[:, 1], 0, height - 1)
    arr[:, 2] = _np.clip(arr[:, 2], 0, width - 1)
    arr[:, 3] = _np.clip(arr[:, 3], 0, height - 1)
    return arr


def bbox_random_crop_with_constraints(bbox, size, min_scale=0.3,
                                      max_scale=1, max_aspect_ratio=2,
                                      constraints=None, max_trial=50):
    """SSD-style random crop: try crops until one satisfies a min-IoU
    constraint (reference: utils.py:330). Returns (new_bbox,
    (x, y, w, h))."""
    if constraints is None:
        constraints = ((0.1, None), (0.3, None), (0.5, None),
                       (0.7, None), (0.9, None), (None, 1))
    w, h = size
    bbox = _as_np(bbox)
    candidates = [(0, 0, w, h)]
    for min_iou, max_iou in constraints:
        lo = -_np.inf if min_iou is None else min_iou
        hi = _np.inf if max_iou is None else max_iou
        for _ in range(max_trial):
            scale = _pyrandom.uniform(min_scale, max_scale)
            aspect = _pyrandom.uniform(
                max(1 / max_aspect_ratio, scale * scale),
                min(max_aspect_ratio, 1 / (scale * scale)))
            crop_h = int(h * scale / _np.sqrt(aspect))
            crop_w = int(w * scale * _np.sqrt(aspect))
            if crop_w > w or crop_h > h:
                continue
            crop_t = _pyrandom.randrange(h - crop_h + 1)
            crop_l = _pyrandom.randrange(w - crop_w + 1)
            crop_bb = _np.array((crop_l, crop_t, crop_l + crop_w,
                                 crop_t + crop_h))
            if len(bbox) == 0:
                top, bottom = crop_t, crop_t + crop_h
                left, right = crop_l, crop_l + crop_w
                return bbox, (left, top, right - left, bottom - top)
            iou = bbox_iou(bbox, crop_bb[None])
            if lo <= iou.min() and iou.max() <= hi:
                top, bottom = crop_t, crop_t + crop_h
                left, right = crop_l, crop_l + crop_w
                candidates.append((left, top, right - left,
                                   bottom - top))
                break
    # pick a random candidate that keeps at least one box
    while candidates:
        crop = candidates.pop(_np.random.randint(0, len(candidates)))
        new_bbox = bbox_crop(bbox, crop, allow_outside_center=False)
        if len(new_bbox) < 1:
            continue
        return new_bbox, crop
    return bbox, (0, 0, w, h)
