"""Contrib vision transforms (reference: .../vision/transforms/)."""
from . import bbox  # noqa: F401
