"""Prebuilt image / detection DataLoaders (reference:
gluon/contrib/data/vision/dataloader.py — create_image_augment,
ImageDataLoader, create_bbox_augment, ImageBboxDataLoader).

The reference wraps ImageRecord/list datasets with a C++-backed augment
chain; here the augment chains compose the python transform Blocks (the
decode stays in the dataset, the tensor work in XLA)."""
from __future__ import annotations

import numpy as _np

from mxnet_tpu import numpy as _mxnp
from mxnet_tpu.gluon.block import Block
from mxnet_tpu.gluon.data.dataloader import DataLoader
from mxnet_tpu.gluon.data.dataset import Dataset
from mxnet_tpu.gluon.data.vision import transforms as T
from .transforms.bbox import (
    ImageBboxRandomCropWithConstraints,
    ImageBboxRandomExpand,
    ImageBboxRandomFlipLeftRight,
    ImageBboxResize,
)

__all__ = ["create_image_augment", "ImageDataLoader",
           "create_bbox_augment", "ImageBboxDataLoader"]


def create_image_augment(data_shape, resize=0, rand_crop=False,
                         rand_resize=False, rand_mirror=False, mean=None,
                         std=None, brightness=0, contrast=0, saturation=0,
                         hue=0, pca_noise=0, rand_gray=0, inter_method=2,
                         dtype="float32"):  # noqa: ARG001
    """Compose a classification augment chain (reference:
    dataloader.py:34). Returns a transform Block for (H, W, C) uint8."""
    chain = []
    if resize > 0:
        chain.append(T.Resize(resize))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        chain.append(T.RandomResizedCrop(crop_size))
    elif rand_crop:
        chain.append(T.RandomCrop(crop_size))
    else:
        chain.append(T.CenterCrop(crop_size))
    if rand_mirror:
        chain.append(T.RandomFlipLeftRight())
    if brightness:
        chain.append(T.RandomBrightness(brightness))
    if contrast:
        chain.append(T.RandomContrast(contrast))
    if saturation:
        chain.append(T.RandomSaturation(saturation))
    if pca_noise:
        chain.append(T.RandomLighting(pca_noise))
    chain.append(T.ToTensor())
    if mean is not None or std is not None:
        chain.append(T.Normalize(
            mean if mean is not None else 0.0,
            std if std is not None else 1.0))
    return T.Compose(chain)


class _ListDataset(Dataset):
    """(image, label) pairs from arrays/paths, with a transform applied
    to the image."""

    def __init__(self, samples, transform=None, pair_transform=None):
        self._samples = samples
        self._transform = transform
        self._pair_transform = pair_transform

    def __len__(self):
        return len(self._samples)

    def __getitem__(self, idx):
        img, label = self._samples[idx]
        if not hasattr(img, "asnumpy"):
            img = _mxnp.array(_np.asarray(img))
        if self._pair_transform is not None:
            img, label = self._pair_transform(img, label)
        if self._transform is not None:
            img = self._transform(img)
        return img, label


class ImageDataLoader:
    """Classification loader over an image dataset (reference:
    dataloader.py:140). Accepts a Dataset of (image, label) or an
    explicit `dataset=`; augment via `aug_list` or the create_image_
    augment kwargs."""

    def __init__(self, batch_size, data_shape, dataset=None, aug_list=None,
                 shuffle=False, num_workers=0, last_batch="keep",
                 **augment_kwargs):
        if dataset is None:
            raise ValueError("dataset is required (record-file datasets: "
                             "use io.ImageRecordIter)")
        if aug_list is None:
            aug_list = create_image_augment(data_shape, **augment_kwargs)
        elif isinstance(aug_list, (list, tuple)):
            aug_list = T.Compose(list(aug_list))
        ds = _ListDataset(dataset, transform=aug_list)
        self._loader = DataLoader(ds, batch_size=batch_size,
                                  shuffle=shuffle,
                                  num_workers=num_workers,
                                  last_batch=last_batch)

    def __iter__(self):
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)


def create_bbox_augment(data_shape, rand_crop=0, rand_pad=0, rand_gray=0,
                        rand_mirror=False, mean=None, std=None,
                        brightness=0, contrast=0, saturation=0,
                        pca_noise=0, hue=0, inter_method=2,  # noqa: ARG001
                        max_aspect_ratio=2, area_range=(0.3, 3.0),
                        max_attempts=50, pad_val=(127, 127, 127)):
    """Compose a detection augment chain operating on (img, bbox) pairs
    (reference: dataloader.py:246). Color augmentations ride the image
    module's augmenters (image/image.py), borrowed box-unchanged like the
    reference's DetBorrowAug."""
    from mxnet_tpu.image import image as _img

    pair = []

    class _Borrow(Block):
        """Apply an image-only augmenter, passing boxes through."""

        def __init__(self, aug):
            super().__init__()
            self._aug = aug

        def forward(self, img, bbox):
            orig_uint8 = str(getattr(img, "dtype", "")).startswith("uint8")
            out = self._aug(img)
            if orig_uint8 and str(out.dtype) != "uint8":
                # color augs work in float; the PIL-backed resize later
                # in the chain needs uint8 back
                out = _mxnp.clip(out, 0, 255).astype("uint8")
            return out, bbox

    color_augs = []
    if brightness or contrast or saturation:
        color_augs.append(_img.ColorJitterAug(brightness, contrast,
                                              saturation))
    if hue:
        color_augs.append(_img.HueJitterAug(hue))
    if pca_noise:
        color_augs.append(_img.LightingAug(
            pca_noise, _img.PCA_EIGVAL, _img.PCA_EIGVEC))
    if rand_gray:
        color_augs.append(_img.RandomGrayAug(rand_gray))
    pair.extend(_Borrow(a) for a in color_augs)
    if rand_crop > 0:
        pair.append(ImageBboxRandomCropWithConstraints(
            p=rand_crop, min_scale=area_range[0],
            max_scale=min(1.0, area_range[1]),
            max_aspect_ratio=max_aspect_ratio, max_trial=max_attempts))
    if rand_pad > 0:
        pair.append(ImageBboxRandomExpand(
            p=rand_pad, max_ratio=area_range[1],
            fill=pad_val[0] if isinstance(pad_val, (tuple, list))
            else pad_val))
    if rand_mirror:
        pair.append(ImageBboxRandomFlipLeftRight(0.5))
    pair.append(ImageBboxResize(data_shape[2], data_shape[1]))

    class _Chain(Block):
        def forward(self, img, bbox):
            for t in pair:
                img, bbox = t(img, bbox)
            return img, bbox

    return _Chain()


class ImageBboxDataLoader:
    """Detection loader yielding (images, padded bboxes) (reference:
    dataloader.py:364). `dataset`: sequence of (image, bbox (N, 4+))."""

    def __init__(self, batch_size, data_shape, dataset=None, aug_list=None,
                 shuffle=False, num_workers=0, last_batch="keep",
                 coord_normalized=True, **augment_kwargs):
        if dataset is None:
            raise ValueError("dataset is required")
        if aug_list is None:
            aug_list = create_bbox_augment(data_shape, **augment_kwargs)
        self._coord_normalized = coord_normalized
        ds = _ListDataset(dataset, pair_transform=aug_list)
        self._loader = DataLoader(
            ds, batch_size=batch_size, shuffle=shuffle,
            num_workers=num_workers, last_batch=last_batch,
            batchify_fn=self._batchify)

    @staticmethod
    def _normalize(img, bbox):
        arr = bbox.asnumpy() if hasattr(bbox, "asnumpy") else \
            _np.asarray(bbox)
        h, w = (img.shape[0], img.shape[1])
        arr = _np.array(arr, dtype=_np.float64, copy=True)
        arr[:, 0] /= w
        arr[:, 2] /= w
        arr[:, 1] /= h
        arr[:, 3] /= h
        return arr

    def _batchify(self, samples):
        """Pad per-image bboxes to the batch max with -1 rows (the
        reference's detection batchify)."""
        imgs, bboxes = zip(*samples)
        arrs = [b.asnumpy() if hasattr(b, "asnumpy") else _np.asarray(b)
                for b in bboxes]
        if self._coord_normalized:
            arrs = [self._normalize(i, b) for i, b in zip(imgs, arrs)]
        maxn = max(len(b) for b in arrs)
        width = max(a.shape[1] for a in arrs)
        padded = _np.full((len(arrs), maxn, width), -1.0, _np.float32)
        for i, b in enumerate(arrs):
            if len(b):
                padded[i, :len(b), :b.shape[1]] = b
        imgs = _np.stack([i.asnumpy() if hasattr(i, "asnumpy")
                          else _np.asarray(i) for i in imgs])
        return _mxnp.array(imgs), _mxnp.array(padded)

    def __iter__(self):
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)
