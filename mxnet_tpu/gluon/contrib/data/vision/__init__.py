"""Contrib vision data utilities (reference: gluon/contrib/data/vision/)."""
from . import transforms  # noqa: F401
from .dataloader import ImageBboxDataLoader, ImageDataLoader  # noqa: F401
