"""Loss layers (reference: python/mxnet/gluon/loss.py, 1009 LoC)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import apply_op
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CosineEmbeddingLoss",
           "PoissonNLLLoss", "CTCLoss", "SDMLLoss"]


def _reduce(x, weight, sample_weight, batch_axis):
    if sample_weight is not None:
        x = x * sample_weight
    if weight is not None:
        x = x * weight
    axes = tuple(i for i in range(x.ndim) if i != batch_axis)
    return jnp.mean(x, axis=axes) if axes else x


class Loss(HybridBlock):
    def __init__(self, weight=None, batch_axis=0):
        super().__init__()
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{type(self).__name__}(batch_axis={self._batch_axis}, w={self._weight})"


class L2Loss(Loss):
    """0.5*(pred-label)^2 (reference: loss.py:L2Loss)."""

    def __init__(self, weight=1.0, batch_axis=0):
        super().__init__(weight, batch_axis)

    def forward(self, pred, label, sample_weight=None):
        w, ba = self._weight, self._batch_axis

        def fn(p, l, sw=None):  # noqa: E741
            loss = jnp.square(l.reshape(p.shape) - p) / 2.0
            return _reduce(loss, w, sw, ba)

        if sample_weight is not None:
            return apply_op(fn, pred, label, sample_weight)
        return apply_op(fn, pred, label)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)

    def forward(self, pred, label, sample_weight=None):
        w, ba = self._weight, self._batch_axis

        def fn(p, l, sw=None):  # noqa: E741
            return _reduce(jnp.abs(l.reshape(p.shape) - p), w, sw, ba)

        if sample_weight is not None:
            return apply_op(fn, pred, label, sample_weight)
        return apply_op(fn, pred, label)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE over logits (reference: SigmoidBCELoss)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        w, ba, fs = self._weight, self._batch_axis, self._from_sigmoid

        def fn(p, l, sw=None, pw=None):  # noqa: E741
            l2 = l.reshape(p.shape)
            if not fs:
                if pw is None:
                    loss = (jnp.maximum(p, 0) - p * l2
                            + jnp.log1p(jnp.exp(-jnp.abs(p))))
                else:
                    # reference loss.py:268-272: log_weight = 1+(pw-1)*y;
                    # loss = x - x*y + log_weight*(softplus(-|x|)+relu(-x))
                    log_weight = 1 + (pw - 1) * l2
                    loss = (p - p * l2
                            + log_weight * (jnp.log1p(jnp.exp(-jnp.abs(p)))
                                            + jnp.maximum(-p, 0)))
            else:
                eps = 1e-12
                pos = l2 * jnp.log(p + eps)
                if pw is not None:
                    pos = pos * pw
                loss = -(pos + (1 - l2) * jnp.log(1 - p + eps))
            return _reduce(loss, w, sw, ba)

        # apply_op forwards None args untouched, so one call covers all
        # sample_weight/pos_weight combinations
        return apply_op(fn, pred, label, sample_weight, pos_weight)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax CE (reference: SoftmaxCrossEntropyLoss).

    sparse_label=True takes class indices; else one-hot/probabilities."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._axis = axis
        self._sparse = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        axis, sparse, logits = self._axis, self._sparse, self._from_logits
        w, ba = self._weight, self._batch_axis

        def fn(p, l, sw=None):  # noqa: E741
            logp = p if logits else jax.nn.log_softmax(p, axis=axis)
            if sparse:
                li = l.astype(jnp.int32)
                ax = axis % logp.ndim
                lshape = logp.shape[:ax] + logp.shape[ax + 1:]
                picked = jnp.take_along_axis(
                    logp, jnp.expand_dims(li.reshape(lshape), ax), axis=ax)
                loss = -jnp.squeeze(picked, ax)
            else:
                loss = -jnp.sum(logp * l.reshape(logp.shape), axis=axis)
            return _reduce(loss, w, sw, ba)

        if sample_weight is not None:
            return apply_op(fn, pred, label, sample_weight)
        return apply_op(fn, pred, label)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        fl, axis, w, ba = self._from_logits, self._axis, self._weight, \
            self._batch_axis

        def fn(p, l, sw=None):  # noqa: E741
            logp = p if fl else jax.nn.log_softmax(p, axis=axis)
            loss = l * (jnp.log(l + 1e-12) - logp)
            return _reduce(jnp.mean(loss, axis=axis), w, sw, ba)

        if sample_weight is not None:
            return apply_op(fn, pred, label, sample_weight)
        return apply_op(fn, pred, label)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        rho, w, ba = self._rho, self._weight, self._batch_axis

        def fn(p, l, sw=None):  # noqa: E741
            d = jnp.abs(l.reshape(p.shape) - p)
            loss = jnp.where(d > rho, d - 0.5 * rho, 0.5 / rho * d * d)
            return _reduce(loss, w, sw, ba)

        if sample_weight is not None:
            return apply_op(fn, pred, label, sample_weight)
        return apply_op(fn, pred, label)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        m, w, ba = self._margin, self._weight, self._batch_axis

        def fn(p, l, sw=None):  # noqa: E741
            return _reduce(jnp.maximum(0.0, m - p * l.reshape(p.shape)),
                           w, sw, ba)

        if sample_weight is not None:
            return apply_op(fn, pred, label, sample_weight)
        return apply_op(fn, pred, label)


class SquaredHingeLoss(HingeLoss):
    def forward(self, pred, label, sample_weight=None):
        m, w, ba = self._margin, self._weight, self._batch_axis

        def fn(p, l, sw=None):  # noqa: E741
            return _reduce(
                jnp.square(jnp.maximum(0.0, m - p * l.reshape(p.shape))),
                w, sw, ba)

        if sample_weight is not None:
            return apply_op(fn, pred, label, sample_weight)
        return apply_op(fn, pred, label)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed"):
        super().__init__(weight, batch_axis)
        self._fmt = label_format

    def forward(self, pred, label, sample_weight=None):
        fmt, w, ba = self._fmt, self._weight, self._batch_axis

        def fn(p, l, sw=None):  # noqa: E741
            l2 = l.reshape(p.shape)
            if fmt == "binary":
                l2 = 2 * l2 - 1
            loss = jnp.log1p(jnp.exp(-p * l2))
            return _reduce(loss, w, sw, ba)

        if sample_weight is not None:
            return apply_op(fn, pred, label, sample_weight)
        return apply_op(fn, pred, label)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):  # noqa: ARG002
        m, w, ba = self._margin, self._weight, self._batch_axis

        def fn(p, pos, neg):
            axes = tuple(range(1, p.ndim))
            loss = jnp.sum(jnp.square(p - pos) - jnp.square(p - neg),
                           axis=axes) + m
            return _reduce(jnp.maximum(loss, 0.0), w, None, ba)

        return apply_op(fn, pred, positive, negative)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):  # noqa: ARG002
        m, w, ba = self._margin, self._weight, self._batch_axis

        def fn(a, b, l):  # noqa: E741
            cos = jnp.sum(a * b, -1) / (
                jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
                + 1e-12)
            l2 = l.reshape(cos.shape)
            loss = jnp.where(l2 == 1, 1 - cos,
                             jnp.maximum(0.0, cos - m))
            return _reduce(loss, w, None, ba)

        return apply_op(fn, input1, input2, label)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False):
        super().__init__(weight, batch_axis)
        self._from_logits = from_logits
        self._full = compute_full

    def forward(self, pred, label, sample_weight=None, epsilon=1e-08):
        fl, full, w, ba = self._from_logits, self._full, self._weight, \
            self._batch_axis

        def fn(p, l, sw=None):  # noqa: E741
            t = l.reshape(p.shape)
            if fl:
                loss = jnp.exp(p) - t * p
            else:
                loss = p - t * jnp.log(p + epsilon)
            if full:
                stirling = (t * jnp.log(t + epsilon) - t
                            + 0.5 * jnp.log(2 * jnp.pi * (t + epsilon)))
                loss = loss + jnp.where(t > 1, stirling,
                                        jnp.zeros_like(stirling))
            return _reduce(loss, w, sw, ba)

        if sample_weight is not None:
            return apply_op(fn, pred, label, sample_weight)
        return apply_op(fn, pred, label)


class CTCLoss(Loss):
    """CTC loss (reference: loss.py:CTCLoss over src/operator/nn/ctc_loss.cc
    / warp-ctc). Implemented over optax.ctc_loss (XLA-lowered)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 padding_value=-1, blank_id=0):
        super().__init__(weight, 0)
        assert layout in ("NTC", "TNC")
        self._layout = layout
        self._label_layout = label_layout
        # gluon contract: labels padded with -1 (reference loss.py:497);
        # the nd.ctc_loss op overrides to 0 for blank_label='first'
        self._padding_value = padding_value
        self._blank_id = blank_id

    def forward(self, pred, label, pred_lengths=None, label_lengths=None):
        import optax

        layout, w = self._layout, self._weight
        pad_val, blank = self._padding_value, self._blank_id

        def fn(p, l, pl=None, ll=None):  # noqa: E741
            if layout == "TNC":
                p = jnp.swapaxes(p, 0, 1)
            n, t = p.shape[0], p.shape[1]
            logitpad = jnp.zeros((n, t)) if pl is None else (
                jnp.arange(t)[None, :] >= pl[:, None]).astype(p.dtype)
            lt = l.shape[1]
            if ll is None:
                # infer lengths: cut at the first padding value
                # (reference ctc_loss.cc LabelTensorToPackedVector)
                is_pad = l == pad_val
                ll = jnp.where(is_pad.any(axis=1),
                               is_pad.argmax(axis=1), lt)
            labelpad = (jnp.arange(lt)[None, :]
                        >= ll[:, None]).astype(p.dtype)
            loss = optax.ctc_loss(p, logitpad, l.astype(jnp.int32),
                                  labelpad, blank_id=blank)
            if w is not None:
                loss = loss * w
            return loss

        return apply_op(fn, pred, label, pred_lengths, label_lengths)


class SDMLLoss(Loss):
    """Batchwise Smoothed Deep Metric Learning loss (reference:
    loss.py:902, arXiv:1905.12786): every off-diagonal item in the
    aligned minibatch pair (x1[i], x2[i]) acts as a negative; the KL
    between log-softmax of negative distances and a label-smoothed
    identity trains similarity. Returns per-row losses."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._smooth = smoothing_parameter

    def forward(self, x1, x2):
        smooth = self._smooth
        if x1.shape[0] < 2:
            raise ValueError(
                "SDMLLoss needs batch_size >= 2 (off-diagonal rows are "
                "the negatives; a 1-row batch has none and the label "
                "smoothing divides by n-1)")

        def fn(a, b):
            n = a.shape[0]
            d = jnp.sum((a[:, None, :] - b[None, :, :]) ** 2, axis=2)
            logp = jax.nn.log_softmax(-d, axis=1)
            eye = jnp.eye(n, dtype=a.dtype)
            labels = eye * (1 - smooth) + (1 - eye) * smooth / (n - 1)
            # KLDivLoss(from_logits=True) semantics: mean over classes of
            # label * (log label - logp). No batch_size rescale: the
            # reference dropped it in PR#18423 (loss.py:1006-1008).
            kl = labels * (jnp.log(jnp.maximum(labels, 1e-12)) - logp)
            loss = jnp.mean(kl, axis=1)
            if self._weight is not None:
                loss = loss * self._weight
            return loss

        return apply_op(fn, x1, x2, name="sdml_loss")
