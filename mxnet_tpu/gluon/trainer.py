"""Gluon Trainer (reference: python/mxnet/gluon/trainer.py:79).

Applies an Optimizer to a set of Parameters, with gradient aggregation
through a KVStore: per-device gradients are summed (pushpull) and every
device's weight copy updated — the reference's `_allreduce_grads` +
`_update` path (trainer.py:402,451). With kvstore='tpu_dist' the aggregation
is an XLA collective; update_on_kvstore=True runs the optimizer inside the
store (the dist server analog).
"""
from __future__ import annotations

import pickle
import time

from .. import optimizer as opt_mod
from ..diagnostics import spans as _spans
from ..telemetry import instruments as _telemetry
from ..kvstore import KVStoreBase, create as kv_create
from ..ndarray.ndarray import NDArray
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore=None,
                 compression_params=None, update_on_kvstore=None,
                 batch_axis=0, mesh=None, sharding_plan=None):  # noqa: ARG002
        if isinstance(params, dict):
            param_list = [params[k] for k in sorted(params)]
            self._param_names = sorted(params)
        elif isinstance(params, (list, tuple)):
            param_list = list(params)
            self._param_names = [p.name for p in param_list]
        else:
            raise ValueError("params must be dict/list of Parameters")
        for p in param_list:
            if not isinstance(p, Parameter):
                raise ValueError(f"expected Parameter, got {type(p)}")
        self._params = param_list
        self._scale = 1.0
        optimizer_params = optimizer_params or {}
        self._optimizer = opt_mod.create(optimizer, **optimizer_params) \
            if not isinstance(optimizer, opt_mod.Optimizer) else optimizer
        self._optimizer.param_dict = dict(enumerate(self._params))
        self._states = [None] * len(self._params)
        self._states_created = [False] * len(self._params)
        if isinstance(kvstore, KVStoreBase):
            self._kvstore = kvstore
        elif isinstance(kvstore, str) and kvstore not in (None, "None"):
            self._kvstore = kv_create(kvstore)
        else:
            self._kvstore = None
        if compression_params is not None:
            if self._kvstore is None:
                raise ValueError(
                    "compression_params requires a kvstore")
            self._kvstore.set_gradient_compression(compression_params)
        self._update_on_kvstore = bool(update_on_kvstore) and \
            self._kvstore is not None
        if self._update_on_kvstore:
            self._kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = False
        # hybrid parallelism (mxnet_tpu/sharding; docs/sharding.md):
        # mesh= is the axes shorthand (Trainer(..., mesh=(('dp', -1),))),
        # sharding_plan= the full object; resolve_plan folds in
        # MXTPU_MESH/MXTPU_SHARDING, returning None when the subsystem is
        # off or nothing names a mesh — that None keeps every path below
        # bitwise-identical to the unsharded trainer.
        from ..sharding import resolve_plan as _resolve_plan

        self._sharding_plan = _resolve_plan(
            sharding_plan if sharding_plan is not None else mesh)
        self._plan_applied = False
        if self._sharding_plan is not None and self._kvstore is not None:
            setter = getattr(self._kvstore, "set_sharding_plan", None)
            if setter is not None:
                setter(self._sharding_plan)
        self._maybe_apply_plan()
        self._last_step_end = None  # telemetry: previous step() finish
        # param index -> grad buffer version seen at its last update;
        # a matching version means the grad is STALE (nothing backprop'd
        # into it since) — see update()/allreduce_grads()
        self._grad_versions = {}

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def sharding_plan(self):
        """The resolved ShardingPlan, or None (unsharded)."""
        return self._sharding_plan

    def set_sharding_plan(self, plan):
        """Swap this trainer onto a new ShardingPlan (or None ->
        replicated) — the elastic re-entry hook (mxnet_tpu/elastic;
        docs/elasticity.md). Re-places params + grads under the new
        plan immediately when params are live, and re-places created
        optimizer state per the new plan's ZeRO state specs, so state
        saved 1/N along one fsdp axis re-extends along the new one.
        Callers owning a TrainStep must also call its rebuild() — the
        compiled whole-step program bakes the old mesh in."""
        self._sharding_plan = plan
        self._plan_applied = False
        if self._kvstore is not None:
            setter = getattr(self._kvstore, "set_sharding_plan", None)
            if setter is not None:
                setter(plan)
        if plan is None:
            # dropping to replicated: pull live params/grads/state back
            # onto the default device — an old mesh placement left in
            # place poisons the next compiled program with mixed-device
            # operands
            import jax

            if not any(p._data_map is None for p in self._params):
                dev = jax.devices()[0]
                for i, p in enumerate(self._params):
                    for arr in p._data_map.values():
                        arr._data = jax.device_put(arr._data, dev)
                        arr._version += 1
                        if arr._grad is not None:
                            arr._grad._data = jax.device_put(
                                arr._grad._data, dev)
                            arr._grad._version += 1
                    if self._states_created[i]:
                        opt_mod.place_state_like(self._states[i],
                                                 p.data())
            return
        self._maybe_apply_plan()
        if self._plan_applied:
            for i, p in enumerate(self._params):
                if self._states_created[i]:
                    opt_mod.place_state_like(
                        self._states[i], p.data(), plan=plan,
                        name=self._param_names[i])

    def _maybe_apply_plan(self):
        """Place every param (+grads) per the plan, once all params are
        initialized.  Deferred-shape models initialize at first forward,
        so this is re-checked lazily from __init__, step()/update(), and
        TrainStep — it no-ops after the first successful application and
        instantly when there is no plan."""
        plan = self._sharding_plan
        if plan is None or self._plan_applied:
            return
        if any(p._data_map is None for p in self._params):
            return  # deferred init still pending; try again next call
        plan.apply(dict(zip(self._param_names, self._params)),
                   label="trainer")
        self._plan_applied = True

    def _ensure_states(self, i, weight):
        if not self._states_created[i]:
            self._states[i] = self._optimizer.create_state_multi_precision(
                i, weight)
            self._states_created[i] = True
            if self._plan_applied:
                # optimizer state (momentum, fp32 master copies, fused
                # bucket slices) mirrors its weight's shape — give it
                # the weight's placement so updates stay local to each
                # shard instead of pulling state cross-device; under a
                # ZeRO plan (fsdp axis + MXTPU_ZERO) it lands on the
                # sharded-bucket layout instead, 1/N per rank
                opt_mod.place_state_like(
                    self._states[i], weight, plan=self._sharding_plan,
                    name=self._param_names[i])

    def allreduce_grads(self, ignore_stale_grad=False):
        """Aggregate gradients across device copies via the kvstore
        (reference: trainer.py:402 _allreduce_grads).

        With the fused path on (MXTPU_FUSED_UPDATE, default) all params
        go to the store in ONE list-form pushpull, which tpu_dist turns
        into a bucketed flat allreduce — one reduce dispatch per ~25 MB
        dtype-homogeneous buffer instead of one per param. Otherwise
        calls are issued per param in descending priority (priority=-i,
        so layer 0 first — its weights gate the next forward), the P3
        dispatch-order contract (src/kvstore/p3store_dist.h).

        `ignore_stale_grad` skips params whose grad buffer is STALE
        (untouched since their last update): reducing one would both sum
        garbage into live gradients and bump the buffer's version, making
        update() mistake it for fresh.
        """
        kv = self._kvstore
        if kv is None:
            return
        distributed = getattr(kv, "num_workers", 1) > 1 or \
            kv.is_capable("pushpull")
        from .. import env as _env

        fused = _env.get("MXTPU_FUSED_UPDATE")
        keys, vals = [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            grads = p.list_grad()
            if len(grads) == 1 and not distributed:
                continue  # single copy, local store: nothing to reduce
            if ignore_stale_grad and \
                    self._grad_versions.get(i) == grads[0]._version:
                continue
            if fused:
                keys.append(i)
                vals.append(grads)
            else:
                kv.pushpull(i, grads, out=grads, priority=-i)
        if fused and keys:
            if len(keys) == 1:
                kv.pushpull(keys[0], vals[0], out=vals[0], priority=0)
            else:
                kv.pushpull(keys, vals, out=vals, priority=0)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + optimizer update, scaling grads by 1/batch_size
        (reference: trainer.py:341)."""
        self._optimizer.rescale_grad = self._scale / batch_size
        self._maybe_apply_plan()
        with _spans.span("allreduce_grads", cat="collective"):
            self.allreduce_grads(ignore_stale_grad)
        with _spans.span("optimizer_update", cat="optimizer"):
            self.update(batch_size, ignore_stale_grad, _skip_rescale=True)
        self._record_step_complete(batch_size)

    def _record_step_complete(self, batch_size):
        """Per-iteration bookkeeping shared by step() and the whole-step
        compiled path (gluon.TrainStep): close the span bucket, time the
        step interval."""
        # close this iteration's step bucket: fwd/bwd spans recorded since
        # the previous step() and the update phases all share one index
        _spans.mark_step()
        # step-time = interval between consecutive step() completions, so
        # the histogram sees the FULL iteration (data + fwd + bwd + update
        # dispatch); the first step is counted but not timed. The MFU
        # gauge follows when telemetry.set_flop_budget() declared a
        # per-step FLOP cost (docs/telemetry.md).
        now = time.perf_counter()
        last = self._last_step_end
        self._last_step_end = now
        _telemetry.observe_step(
            None if last is None else now - last, examples=batch_size)
        try:
            # the flight recorder's per-step heartbeat: carries enough to
            # read training health off a postmortem (loss arrives via
            # flight.record_loss when a loop host-syncs it)
            from ..observability import flight as _flight

            _flight.record(
                "step", examples=batch_size,
                lr=getattr(self._optimizer, "learning_rate", None),
                dt=None if last is None else now - last)
        except Exception:
            pass

    def update(self, batch_size, ignore_stale_grad=False,
               _skip_rescale=False):
        if not _skip_rescale:
            self._optimizer.rescale_grad = self._scale / batch_size
            self._maybe_apply_plan()
        from .. import env as _env

        # fused multi-tensor path (default): single-device dense params
        # are collected into ONE list-form update_multi_precision call —
        # the optimizer buckets them by (dtype, multi-precision) and runs
        # one donated jit dispatch per bucket. Sparse grads and params
        # replicated across devices stay on the legacy per-param loop.
        fuse = _env.get("MXTPU_FUSED_UPDATE") and \
            self._optimizer._supports_fused()
        f_idx, f_w, f_g, f_s = [], [], [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            p._check_initialized()
            devs = p.list_ctx()
            for dev in devs:
                w = p.data(dev)
                g = p.grad(dev)
                # stale = grad buffer untouched since the last update
                # (reference: Parameter._fresh_grad per-step flag)
                fresh = self._grad_versions.get(i) != g._version
                if not ignore_stale_grad or fresh:
                    self._ensure_states(i, w)
                    if getattr(p, "grad_stype", "default") == "row_sparse":
                        # hand the optimizer only the touched rows
                        # (lazy_update semantics; Parameter docs)
                        self._optimizer.update_multi_precision(
                            i, w, p._as_row_sparse_grad(g),
                            self._states[i])
                    elif fuse and len(devs) == 1:
                        f_idx.append(i)
                        f_w.append(w)
                        f_g.append(g)
                        f_s.append(self._states[i])
                    else:
                        self._optimizer.update_multi_precision(
                            i, w, g, self._states[i])
                    self._grad_versions[i] = g._version
                break  # update primary; replicate below
            if len(p.list_ctx()) > 1:
                primary = p.data(p.list_ctx()[0])
                for dev in p.list_ctx()[1:]:
                    primary.copyto(p.data(dev))
        if f_idx:
            self._optimizer.update_fused(f_idx, f_w, f_g, f_s,
                                         multi_precision=True)

    def zero_grad(self):
        for p in self._params:
            if p.grad_req != "null" and p._data_map is not None:
                p.zero_grad()

    # -- checkpoint --------------------------------------------------------
    # (For complete atomic checkpoints — params + states + RNG + resume —
    # use mx.checkpoint.CheckpointManager; these two round-trip ONLY the
    # optimizer side, the reference save_states/load_states contract.)
    def _stale_indices(self):
        """Param indices whose grad buffer is currently STALE (untouched
        since its last update) — the portable form of _grad_versions,
        whose raw buffer versions are process-local."""
        stale = []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data_map is None:
                continue
            grads = p.list_grad()
            if grads and self._grad_versions.get(i) == grads[0]._version:
                stale.append(i)
        return stale

    def save_states(self, fname):
        """Serialize optimizer states (reference: trainer.py:489).

        Format 2 additionally round-trips the fused/legacy-shared state
        bookkeeping (per-param update counts `t`), stale-grad tracking,
        loss scale, and per-param (name, dtype) so load_states can
        reject a payload from a different model instead of mis-zipping.
        """
        def to_np(s):
            if s is None:
                return None
            if isinstance(s, NDArray):
                return s.asnumpy()
            return [to_np(x) for x in s]

        payload = {
            "format": 2,
            "states": [to_np(s) for s in self._states],
            "created": list(self._states_created),
            "num_update": self._optimizer.num_update,  # format-1 readers
            "optimizer": self._optimizer.bookkeeping_state(),
            "param_meta": [
                (p.name, str(p.dtype) if p.dtype is not None else None)
                for p in self._params],
            "stale": self._stale_indices(),
            "scale": self._scale,
        }
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_states(self, fname):
        """Inverse of save_states. Raises ValueError (clear message, no
        state touched) when the payload's param count or dtypes don't
        match this trainer. Format-1 payloads still load."""
        import jax.numpy as jnp

        with open(fname, "rb") as f:
            payload = pickle.load(f)

        states = payload["states"]
        if len(states) != len(self._params):
            raise ValueError(
                f"optimizer-state payload {fname!r} holds "
                f"{len(states)} parameter states but this trainer has "
                f"{len(self._params)} parameters — wrong model or "
                f"stale checkpoint")
        for i, (name, dt) in enumerate(payload.get("param_meta") or []):
            p = self._params[i]
            have = str(p.dtype) if p.dtype is not None else None
            if dt is not None and have is not None and dt != have:
                raise ValueError(
                    f"optimizer-state payload {fname!r}: param {i} "
                    f"({name!r}) was saved with dtype {dt}, trainer "
                    f"param {p.name!r} declares {have}")

        def from_np(s):
            if s is None:
                return None
            if isinstance(s, list):
                return tuple(from_np(x) for x in s)
            return NDArray(jnp.asarray(s))

        self._states = [from_np(s) for s in states]
        self._states_created = list(payload["created"])
        opt_state = payload.get("optimizer")
        if opt_state is not None:
            self._optimizer.load_bookkeeping_state(opt_state)
        else:
            self._optimizer.num_update = payload["num_update"]
        if "scale" in payload:
            self._scale = float(payload["scale"])
        if "stale" in payload:
            # re-mark stale grads against THIS process's buffer versions
            self._grad_versions = {}
            for i in payload["stale"]:
                p = self._params[i]
                if p.grad_req != "null" and p._data_map is not None:
                    grads = p.list_grad()
                    if grads:
                        self._grad_versions[i] = grads[0]._version
