"""Whole-step compiled training: ONE donated jit dispatch per step.

The legacy loop costs three dispatch families per iteration — the
CachedOp forward, its vjp backward, and the fused optimizer buckets
(plus an allreduce per bucket under tpu_dist). `TrainStep` captures the
entire iteration — loss forward, autograd backward, gradient allreduce,
and the PR-4 fused optimizer update — into a single `jax.jit` program:

  * parameter weights and optimizer state are DONATED, so XLA updates
    them in place (no second copy of the model in HBM);
  * per-param lr/wd/update-count enter as weak-typed python scalars —
    the same trick as `Optimizer.update_fused` — so LR schedules change
    values, never signatures: zero retraces after the first step;
  * the forward runs through the exact `_traced_forward` body the
    CachedOp jit uses, the backward is `jax.vjp` seeded with ones (the
    `loss.backward()` contract), and the update unrolls
    `Optimizer._fused_step_body` per (dtype, multi-precision) bucket —
    so the result is BITWISE identical to the three-phase sequence;
  * with a device mesh, forward+backward run under `shard_map` with the
    batch sharded over the data-parallel axis and gradients reduced
    in-program via the kvstore's `traced_allreduce`
    (`collectives.psum_tree_flat_traced`) — reduce and update compile
    into the same XLA program, zero extra collective dispatches;
  * with a TENSOR/FSDP-sharded plan (any plan whose rules or SpecLayout
    shard a parameter dim — `plan.shards_params(...)`), the same step
    body compiles as one donated GSPMD program over the plan's mesh
    instead of `shard_map`: operands enter committed under the plan's
    shardings, gradients are pinned to the ZeRO state specs
    (`plan.state_spec_for`) so XLA lowers the reduce to reduce-scatter,
    updated params are pinned back to the param specs (all-gather), and
    optimizer state stays 1/fsdp per device end to end — ZeRO sharding
    of the fused optimizer buckets with zero eager collectives.

`MXTPU_WHOLE_STEP=0` (or any ineligibility: sparse grads, an optimizer
overriding `update`, `clip_global_norm`, multi-copy params, gradient
compression, a multi-worker store without a mesh) falls back to the
legacy three-phase path — `TrainStep` remains a drop-in way to run a
step either way. Telemetry: `step_dispatch_total{path}` counts
whole_step vs phased executions, `step_donated_bytes` the in-place
buffer reuse; the compile registry gains a `whole_step` entry with the
program's flops and peak-HBM estimate (docs/performance.md).
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from .. import _random
from .. import autograd as ag
from ..diagnostics import introspect as _introspect
from ..diagnostics import spans as _spans
from ..diagnostics import watchdog as _watchdog
from ..ndarray.ndarray import NDArray
from ..optimizer.optimizer import (Optimizer, _cache_size, _donate_enabled,
                                   _donated_bytes, _donation_safe, _specs,
                                   _unwrap, _write_state)
from ..telemetry import instruments as _telemetry
from .block import HybridBlock, _traced_forward
from .parameter import Parameter

__all__ = ["TrainStep"]


def _wrap_tree(datas):
    """Raw-array pytree -> NDArray pytree (what a loss_fn expects)."""
    return jax.tree_util.tree_map(NDArray, datas)


def _numerics_mode():
    """Live MXTPU_NUMERICS mode; 'off' when observability is broken —
    the check layer must never take the training step down."""
    try:
        from ..observability import numerics as _numerics

        return _numerics.mode()
    except Exception:
        return "off"


class TrainStep:
    """One training iteration as a single compiled, donated dispatch.

    ``step = TrainStep(net, loss_fn, trainer)`` then per batch
    ``loss = step(x, y)`` replaces::

        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch_size)

    `net` is a HybridBlock; `loss_fn(out, *labels)` maps the network
    output and the remaining batch elements to a loss NDArray (None
    means the net's output IS the loss). The first `n_data` positional
    batch elements feed the net, the rest feed the loss. `batch_size`
    defaults to the first input's `batch_axis` extent and drives the
    legacy `rescale_grad = scale / batch_size` contract.

    With `mesh=`/`axis=`, forward+backward run under shard_map with the
    batch sharded over `axis` and params replicated; the loss must keep
    its batch dimension (per-sample losses, the gluon convention) so
    shards concatenate back to the global loss. Gradients are summed
    across shards in-program (`kvstore.traced_allreduce` when the
    trainer has a capable store, else the collectives helper directly),
    matching the single-device sum over the full batch.
    """

    def __init__(self, net, loss_fn, trainer, *, n_data=1, batch_axis=0,
                 mesh=None, axis="dp"):
        self._net = net
        self._loss = loss_fn
        self._trainer = trainer
        self._n_data = int(n_data)
        self._batch_axis = int(batch_axis)
        # a trainer carrying a ShardingPlan makes its mesh this step's
        # default — Trainer(kvstore='tpu_dist', mesh=(('dp', -1),)) then
        # trains sharded through this path with no TrainStep arguments.
        # An EXPLICIT mesh= predates the plan subsystem and keeps its
        # exact old semantics (no plan, no ShardingPass).
        self._plan = None
        if mesh is None:
            plan = getattr(trainer, "sharding_plan", None)
            if plan is not None:
                self._plan = plan
                mesh = plan.mesh
                axis = plan.batch_axis
        self._mesh = mesh
        self._axis = axis
        self._built = False
        self._jit_variants = {}     # donate(bool) -> jitted step
        self._traces = 0            # whole-step jit traces (= compiles)
        self._sink_params = []      # aux-updated params, set at trace time
        self._introspecting = False
        self._ineligible = None     # cached reason string, None = eligible
        self._eligibility_checked = False
        self._variant = None
        # True when the plan tensor/FSDP-shards params: the whole-step
        # program then compiles as one GSPMD partition over the plan's
        # mesh instead of the manual-collective shard_map body
        self._tensor_plan = False

    # -- introspection ----------------------------------------------------
    @property
    def last_path(self):
        """'whole_step' or 'phased' — how the most recent call executed."""
        return getattr(self, "_last_path", None)

    def jit_trace_count(self):
        """Whole-step compiles so far — the zero-retrace proof counter
        (mirrors HybridBlock.jit_trace_count)."""
        return self._traces

    def ineligible_reason(self):
        """Why this step permanently runs phased (None when eligible)."""
        return self._ineligible

    def rebuild(self, mesh=None, axis="dp"):
        """Discard the compiled whole-step program and re-adopt the
        trainer's (possibly new) plan — the elastic re-entry hook
        (mxnet_tpu/elastic/reentry.py; docs/elasticity.md). The jitted
        variants, fused buckets, GSPMD shardings, and the cached
        eligibility verdict all bake the old mesh/world in, so a
        topology change must drop them; the next call re-traces ONCE
        for the new world (jit_trace_count() keeps accumulating — the
        zero-retrace proof is 'exactly one more trace after rebuild').
        An explicit ``mesh=`` keeps the legacy no-plan semantics, as in
        __init__."""
        self._plan = None
        if mesh is None:
            plan = getattr(self._trainer, "sharding_plan", None)
            if plan is not None:
                self._plan = plan
                mesh = plan.mesh
                axis = plan.batch_axis
        self._mesh = mesh
        self._axis = axis
        self._built = False
        self._jit_variants = {}
        self._eligibility_checked = False
        self._ineligible = None
        self._variant = None
        self._tensor_plan = False
        self._step_fn = None
        return self

    # -- eligibility -------------------------------------------------------
    def _check_eligibility(self):
        tr = self._trainer
        opt = tr._optimizer
        if not isinstance(self._net, HybridBlock):
            return "net is not a HybridBlock"
        if getattr(self._net, "_dynamic_graph", False):
            return "net fell back to dynamic-graph execution"
        if not opt._supports_fused():
            return (f"{type(opt).__name__} overrides update/"
                    "update_multi_precision or lacks _rule")
        if opt.clip_global_norm is not None:
            return "clip_global_norm needs the host-combined norm pre-pass"
        if tr._update_on_kvstore:
            return "update_on_kvstore runs the optimizer inside the store"
        kv = tr._kvstore
        if kv is not None:
            if getattr(kv, "_compression", None) is not None:
                return "gradient compression is eager-only"
            distributed = getattr(kv, "num_workers", 1) > 1
            if distributed and self._mesh is None:
                return "multi-worker kvstore without a mesh"
            if self._mesh is not None and \
                    not hasattr(kv, "traced_allreduce") and \
                    kv.is_capable("pushpull"):
                return f"kvstore {type(kv).__name__} has no traced reduce"
        block_params = {id(p): n
                        for n, p in self._net.collect_params().items()}
        seen = set()
        for p in tr._params:
            if p.grad_req == "null":
                continue
            if p.grad_req != "write":
                return (f"param {p.name}: grad_req={p.grad_req!r} "
                        "(grad accumulation is eager-only)")
            if getattr(p, "grad_stype", "default") != "default":
                return f"param {p.name}: sparse gradient"
            if id(p) not in block_params:
                return f"param {p.name} is not owned by the net"
            if id(p) in seen:
                return f"param {p.name} appears twice in the trainer"
            seen.add(id(p))
            if p._data_map is not None and len(p.list_ctx()) > 1:
                return f"param {p.name} is replicated across devices"
        if self._plan is not None:
            # a plan that tensor/FSDP-shards params takes the GSPMD
            # whole-step variant: the step body compiles as ONE donated
            # program over the plan's mesh with every operand entering
            # under its plan sharding — XLA's partitioner inserts the
            # tp psums (and the ZeRO reduce-scatter/allgather the state
            # specs demand) IN-TRACE, where the replicated-params
            # shard_map body would need hand-written model collectives
            names_shapes = [(n, p.shape) for n, p in
                            zip(tr._param_names, tr._params)
                            if p.shape is not None]
            self._tensor_plan = self._plan.shards_params(names_shapes)
        return None

    def _eligible(self):
        if not self._eligibility_checked:
            self._ineligible = self._check_eligibility()
            self._eligibility_checked = True
        return self._ineligible is None

    # -- build -------------------------------------------------------------
    def _build(self):
        tr = self._trainer
        net = self._net
        params = sorted(net.collect_params().items())
        self._block_params = params
        name_of = {id(p): n for n, p in params}
        items = []  # (trainer index, block param name, Parameter)
        for i, p in enumerate(tr._params):
            if p.grad_req == "null":
                continue
            p._check_initialized()
            tr._ensure_states(i, p.data())
            items.append((i, name_of[id(p)], p))
        self._train_items = items
        # bucket by (weight dtype, multi-precision) in trainer order —
        # the exact bucketing update_fused(multi_precision=True) builds,
        # so the unrolled update is the same program member-for-member
        import numpy as _np

        buckets = {}
        for i, n, p in items:
            s = tr._states[i]
            w = p.data()
            use_mp = (isinstance(s, tuple) and len(s) == 2
                      and isinstance(s[0], NDArray)
                      and s[0].dtype == _np.float32
                      and w.dtype != _np.float32)
            buckets.setdefault((str(w.dtype), use_mp), []).append(n)
        self._buckets = [(k, names) for k, names in buckets.items()]
        opt = tr._optimizer
        mode_tag = ("gspmd" if self._tensor_plan
                    else "mesh" if self._mesh is not None else "local")
        self._variant = (f"{type(opt).__name__.lower()}"
                         f"-p{len(items)}-b{len(self._buckets)}"
                         f"-{mode_tag}")
        self._step_fn = self._make_step_fn()
        self._built = True

    def _make_step_fn(self):
        tstep = self
        net = self._net
        loss_fn = self._loss
        n_data = self._n_data
        params = self._block_params
        tr = self._trainer
        opt = tr._optimizer
        cls = type(opt)
        clip = opt.clip_gradient
        wdtype = {n: p.data().dtype for _i, n, p in self._train_items}
        bucket_specs = self._buckets
        mesh, axis = self._mesh, self._axis
        kv = tr._kvstore
        tensor = self._tensor_plan
        if tensor:
            # GSPMD whole-step (tensor/FSDP plans): the body computes the
            # GLOBAL batch as one logical program — no manual psum; the
            # partitioner derives every collective from the operand
            # shardings plus these in-trace pins. Pinning grads to the
            # ZeRO state layout is what turns the backward's gradient
            # allreduce into reduce-scatter + local fused update +
            # allgather of the new params (docs/sharding.md).
            from jax.sharding import NamedSharding

            plan = self._plan
            pmesh = plan.mesh
            wshape = {n: p.shape for _i, n, p in self._train_items}
            w_shard = {n: NamedSharding(pmesh, plan.spec_for(n, s))
                       for n, s in wshape.items()}
            s_shard = {n: NamedSharding(pmesh, plan.state_spec_for(n, s))
                       for n, s in wshape.items()}
            self._w_shard, self._s_shard = w_shard, s_shard

            def _pin_state(n, st):
                return jax.tree_util.tree_map(
                    lambda v: jax.lax.with_sharding_constraint(
                        v, s_shard[n])
                    if getattr(v, "shape", None) == wshape[n] else v, st)
        elif mesh is not None:
            reduce_tree = (kv.traced_allreduce
                           if kv is not None
                           and hasattr(kv, "traced_allreduce")
                           else None)
            n_shards = mesh.shape[axis]

        from .. import passes as _passes

        # the forward body enters the whole-step program through the
        # graph-pass pipeline (kind=whole_step_fwd): AMP / remat passes
        # registered on the block rewrite exactly the part of the
        # program they understand, while optimizer state stays outside
        # their reach.  Explicit args (no closure captures) so the
        # pipeline can trace it standalone; resolves to the raw body
        # when no passes apply.
        def block_body(tws_, frozen_, key_, *data_ins):
            pd = dict(frozen_)
            pd.update(tws_)
            out_datas, sink = _traced_forward(
                net, params, True, pd, key_, data_ins)
            # trace-time side effect: which params get aux updates
            tstep._sink_params = list(sink.params)
            return out_datas, tuple(sink.values)

        block_fwd = _passes.wrap_forward(block_body, _passes.PassContext(
            block=net, label="whole_step", variant=self._variant,
            kind="whole_step_fwd", training=True))

        def fwd_bwd(tws, frozen, key, inputs):
            def block_of(t):
                return block_fwd(t, frozen, key, *inputs[:n_data])

            def loss_of(out_datas):
                out = _wrap_tree(out_datas)
                labels = [NDArray(x) for x in inputs[n_data:]]
                loss = loss_fn(out, *labels) if loss_fn is not None \
                    else out
                if not isinstance(loss, NDArray):
                    raise TypeError(
                        "loss_fn must return a single NDArray, got "
                        f"{type(loss).__name__}")
                return loss._data

            # the tape differentiates the COMPILED block as one vjp node
            # and the loss ops outside it; splitting the vjp here mirrors
            # that, and the optimization barriers pin the same program
            # boundaries so XLA's excess-precision pass cannot skip the
            # low-precision rounding the eager path performs at each
            # boundary — that elision is where bf16 runs lose bitwise
            # parity with the three-phase path (fp32 is unaffected: the
            # barriers only forbid cross-boundary fusion of two cheap
            # edge tensors, not the matmul fusion inside each segment)
            out_datas, block_vjp, aux = jax.vjp(
                block_of, tws, has_aux=True)
            out_datas = jax.lax.optimization_barrier(out_datas)
            # loss.backward() contract: seed the cotangent with ones of
            # the loss's own shape/dtype (sum-over-elements gradient)
            loss_data, loss_vjp = jax.vjp(loss_of, out_datas)
            (dout,) = loss_vjp(jnp.ones_like(loss_data))
            (gd,) = block_vjp(jax.lax.optimization_barrier(dout))
            # parity: backward lands cotangents in grad buffers of the
            # PARAM dtype before the optimizer sees them — barrier so the
            # multi-precision update's f32 cast cannot fold back into the
            # grad matmuls and skip this rounding
            gd = jax.lax.optimization_barrier(
                {n: g.astype(wdtype[n]) for n, g in gd.items()})
            return loss_data, gd, aux

        def step(tws, frozen, states, key, lrs, wds, ts, hyper, *inputs):
            # host side effect: runs once per jit trace (one XLA
            # compile), never on cache hits — except AOT introspection
            # re-lowers, which must not count as a user-visible retrace
            if not tstep._introspecting:
                tstep._bump_trace()
            if mesh is None:
                # single copy per param: the tpu_dist pushpull of one
                # replica is an identity sum — nothing to reduce
                loss_data, gd, aux = fwd_bwd(tws, frozen, key, inputs)
            elif tensor:
                # global-batch GSPMD: the backward's cross-dp gradient
                # sum is implicit (the partitioner inserts the psum);
                # pin each grad to its state's ZeRO sharding so the
                # update computes on the LOCAL 1/N shard — grads arrive
                # by reduce-scatter instead of full allreduce
                loss_data, gd, aux = fwd_bwd(tws, frozen, key, inputs)
                gd = {n: jax.lax.with_sharding_constraint(g, s_shard[n])
                      if g.shape == wshape[n] else g
                      for n, g in gd.items()}
            else:
                from jax.sharding import PartitionSpec as P

                from ..parallel.collectives import (psum_tree_flat_traced,
                                                    shard_map)

                def sharded(tws_, frozen_, key_, *ins):
                    loss_d, gd_, aux_ = fwd_bwd(tws_, frozen_, key_, ins)
                    if loss_d.ndim == 0:
                        raise ValueError(
                            "TrainStep with a mesh needs a per-sample "
                            "loss (batch dim kept) so shards concatenate "
                            "back to the global loss; got a scalar")
                    # grads: per-shard sums over local samples — one
                    # flat-bucketed psum completes the global batch sum
                    # inside the SAME program
                    if reduce_tree is not None:
                        gd_ = reduce_tree(gd_, axis)
                    else:
                        gd_ = psum_tree_flat_traced(gd_, axis)
                    # aux (BN running stats): cross-replica mean, the
                    # sync-BN convention for data-parallel stats
                    aux_ = jax.tree_util.tree_map(
                        lambda v: jax.lax.psum(v, axis) / n_shards, aux_)
                    return loss_d, gd_, aux_

                sm = shard_map(
                    sharded, mesh=mesh,
                    in_specs=(P(), P(), P(),
                              *([P(axis)] * len(inputs))),
                    out_specs=(P(axis), P(), P()))
                loss_data, gd, aux = sm(tws, frozen, key, *inputs)
            # fused optimizer update, unrolled per bucket — the exact
            # _fused_jitted math (shared body), fused into this program
            new_ws, new_states = {}, {}
            for (_dtype_s, use_mp), names in bucket_specs:
                nws, nsts = Optimizer._fused_step_body(
                    cls, clip, False, use_mp,
                    [tws[n] for n in names],
                    [states[n] for n in names],
                    [gd[n] for n in names],
                    [lrs[n] for n in names],
                    [wds[n] for n in names],
                    [ts[n] for n in names],
                    1.0, hyper)
                for n, nw, ns in zip(names, nws, nsts):
                    new_ws[n] = nw
                    new_states[n] = ns
            if tensor:
                # pin outputs to their operand shardings: the updated
                # params allgather back to the plan's layout (closing
                # the ZeRO reduce_scatter -> local rule -> allgather
                # cycle inside this one program) and state stays 1/N —
                # in == out shardings is also what lets donation reuse
                # the buffers and the jit cache never re-specialize
                new_ws = {n: jax.lax.with_sharding_constraint(
                    w, w_shard[n]) for n, w in new_ws.items()}
                new_states = {n: _pin_state(n, st)
                              for n, st in new_states.items()}
            return loss_data, new_ws, new_states, aux

        return step

    def _bump_trace(self):
        self._traces += 1
        _telemetry.record_trace("whole_step", self._variant)

    def _jitted(self, donate):
        fn = self._jit_variants.get(donate)
        if fn is None:
            from .. import passes as _passes

            # the whole-step program compiles through the pipeline seam
            # too; the forward body was already rewritten via
            # wrap_forward, so the only shipped pass claiming
            # kind=whole_step is the audit-only KernelPass (when
            # MXTPU_KERNELS is on) — with kernels off this resolves to
            # the plain donated jit
            fn = _passes.apply(self._step_fn, _passes.PassContext(
                label="whole_step", variant=self._variant,
                kind="whole_step", training=True,
                donate_argnums=(0, 2) if donate else (),
                plan=self._plan))
            self._jit_variants[donate] = fn
        return fn

    def _numerics_boundary(self, loss_data, step_args):
        """MXTPU_NUMERICS trip check at the step boundary, BEFORE results
        are written back — a rejected step leaves params/state at their
        pre-step values. ``step`` mode pays no extra host sync: the step
        boundary already waits on the loss, and the effects barrier just
        flushes the callback the device has by then delivered. On a trip
        the recorded program is re-run eagerly (:func:`numerics.bisect`)
        on the live dispatch operands, the attribution lands in an atomic
        postmortem bundle, and :class:`NonFiniteError` carries all of it.
        """
        from ..observability import numerics as _numerics

        jax.block_until_ready(loss_data)
        _numerics.effects_barrier()
        trip = _numerics.take_trip(label_prefix="whole_step")
        if trip is None:
            return
        report = trip.get("equation")  # op mode attributes at the callback
        if report is None:
            with _spans.span("numerics_bisect", cat="sync"):
                self._introspecting = True  # the re-trace is not a retrace
                try:
                    report = _numerics.bisect_callable(
                        self._step_fn, *step_args)
                except Exception:
                    report = None
                finally:
                    self._introspecting = False
            if report is not None:
                trip["equation"] = report
        bundle = None
        try:
            from ..observability import postmortem as _postmortem

            bundle = _postmortem.dump(
                reason="numerics", extra={"numerics_bisect": report})
        except Exception:
            pass
        raise _numerics.NonFiniteError(
            f"non-finite values in the whole-step program at step "
            f"{trip.get('step')}: {_numerics.format_report(report)} "
            f"(postmortem: {bundle})",
            trip=trip, report=report, bundle=bundle)

    # -- execution ---------------------------------------------------------
    def __call__(self, *batch, batch_size=None):
        for a in batch:
            if not isinstance(a, NDArray):
                raise TypeError(
                    f"TrainStep expects NDArray batch elements, got "
                    f"{type(a).__name__}")
        if batch_size is None:
            batch_size = batch[0].shape[self._batch_axis]
        from .. import env as _env

        if not _env.get("MXTPU_WHOLE_STEP"):
            return self._phased(batch, batch_size)
        if not self._built:
            # complete deferred init BEFORE the (cached) eligibility
            # check — it inspects dtypes and device placement
            self._net._ensure_initialized(batch[:self._n_data])
            # deferred-shape params just materialized: the trainer's
            # ShardingPlan (if any) can now place them (no-op otherwise)
            self._trainer._maybe_apply_plan()
        if not getattr(self._net, "_layout_prepared", False):
            # persistent NHWC weight re-layout BEFORE tws/frozen are
            # built: the donated whole-step program then updates the
            # physical (HWIO) buffers in place, never re-transposing
            # (passes/layout.py; MXTPU_LAYOUT=off returns immediately)
            from ..passes import layout as _layout_pass

            _layout_pass.prepare_block(self._net, trainer=self._trainer)
        if not self._eligible():
            return self._phased(batch, batch_size)
        if not self._built:
            self._build()
        return self._whole(batch, batch_size)

    def _phased(self, batch, batch_size):
        """The legacy three-phase sequence (record/forward+loss,
        backward, Trainer.step) — the fallback contract AND the
        reference semantics the whole-step path is proven against."""
        self._last_path = "phased"
        if self._plan is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            # MXTPU_WHOLE_STEP=0 reaches here before __call__'s deferred
            # init ran: materialize params and let the plan place them
            # BEFORE the batch is committed to the mesh below (both are
            # idempotent no-ops otherwise)
            self._net._ensure_initialized(batch[:self._n_data])
            self._trainer._maybe_apply_plan()

            # tensor-sharded plans run here (GSPMD carries the tp axes),
            # but the batch arrives committed to one device while the
            # plan placed params across the mesh — split it along the
            # data axis (replicate when the batch doesn't divide).
            mesh = self._plan.mesh
            dp = self._plan.axis_sizes()[self._plan.batch_axis]
            ax = self._batch_axis

            def _place(a):
                divisible = (len(a.shape) > ax and a.shape[ax] % dp == 0)
                spec = P(*([None] * ax), self._plan.batch_axis) \
                    if divisible else P()
                return NDArray(
                    jax.device_put(a._data, NamedSharding(mesh, spec)))

            batch = tuple(_place(a) for a in batch)
        with ag.record():
            out = self._net(*batch[:self._n_data])
            loss = self._loss(out, *batch[self._n_data:]) \
                if self._loss is not None else out
        loss.backward()
        self._trainer.step(batch_size)
        _telemetry.record_step_dispatch("phased")
        return loss

    def _whole(self, batch, batch_size):
        self._last_path = "whole_step"
        tr = self._trainer
        opt = tr._optimizer
        # the legacy Trainer.step prologue: grads scale by scale/batch
        opt.rescale_grad = tr._scale / batch_size
        # resolve counts/lr/wd in trainer order — the exact sequence
        # update_fused drives, so schedules and Adam's t match bitwise
        lrs, wds, ts = {}, {}, {}
        for i, n, _p in self._train_items:
            opt._update_count(i)
            lrs[n] = opt._get_lr(i)
            wds[n] = opt._get_wd(i)
            ts[n] = opt._index_update_count[i]
        hyper = dict(opt._hyper())
        hyper["rescale_grad"] = opt.rescale_grad
        tws, states = {}, {}
        for i, n, p in self._train_items:
            tws[n] = p.data()._data
            states[n] = jax.tree_util.tree_map(
                _unwrap, tr._states[i],
                is_leaf=lambda x: isinstance(x, NDArray))
        frozen = {n: p.data()._data for n, p in self._block_params
                  if n not in tws}
        key = _random.next_key()
        inputs = [a._data for a in batch]
        if self._mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            rep = NamedSharding(self._mesh, P())
            shd = NamedSharding(self._mesh, P(self._axis))
            if self._tensor_plan:
                # GSPMD whole-step: every operand enters under its PLAN
                # sharding (params on their specs, state on the ZeRO
                # layout, batch over the data axis). plan.apply/
                # place_state_like already put them there, so these are
                # no-op puts after step one — they exist to commit
                # stragglers (a fresh frozen buffer, the RNG key).
                plan = self._plan
                tws = {n: jax.device_put(v, self._w_shard[n])
                       for n, v in tws.items()}
                wshape = {n: p.shape for _i, n, p in self._train_items}
                states = {
                    n: jax.tree_util.tree_map(
                        lambda v, _n=n: jax.device_put(
                            v, self._s_shard[_n])
                        if getattr(v, "shape", None) == wshape[_n]
                        else jax.device_put(v, rep), st)
                    for n, st in states.items()}
                frozen = {
                    n: jax.device_put(v, NamedSharding(
                        self._mesh, plan.spec_for(n, v.shape)))
                    for n, v in frozen.items()}
                key = jax.device_put(key, rep)
                inputs = [jax.device_put(x, shd) for x in inputs]
            else:
                # place operands for the shard_map program — params,
                # state and key replicated, batch split along the data
                # axis; jit refuses arrays committed to a single device
                # otherwise. Replicated-to-replicated puts are no-ops
                # after step one (the program's outputs come back
                # replicated).
                def _rep(v):
                    return jax.device_put(v, rep)

                tws = jax.tree_util.tree_map(_rep, tws)
                states = jax.tree_util.tree_map(_rep, states)
                frozen = jax.tree_util.tree_map(_rep, frozen)
                key = _rep(key)
                inputs = [jax.device_put(x, shd) for x in inputs]
        donate = _donate_enabled() and _donation_safe(
            (tws, states), (frozen, inputs, key))
        nmode = _numerics_mode()
        if donate and nmode != "off":
            # any active mode raises from _numerics_boundary BEFORE the
            # writeback loop, so the live param/state containers must
            # still hold valid (pre-step) buffers for a caller that
            # catches NonFiniteError and resumes; step mode additionally
            # bisects by re-running the recorded program on THESE
            # operands — they must survive the dispatch
            donate = False
        fn = self._jitted(donate)
        before = _cache_size(fn)
        t0 = time.perf_counter()
        with _spans.span("whole_step", cat="fwd"), \
                _watchdog.guard("whole_step"):
            loss_data, new_ws, new_states, aux = fn(
                tws, frozen, states, key, lrs, wds, ts, hyper, *inputs)
        _telemetry.record_step_dispatch(
            "whole_step", _donated_bytes(tws, states) if donate else 0)
        after = _cache_size(fn)
        if after is not None and after != before:
            compile_seconds = time.perf_counter() - t0
            _telemetry.record_compile("whole_step", self._variant,
                                      compile_seconds)
            # AOT cost/memory analysis of the one-dispatch program for
            # the compile registry (tools/diagnose.py whole-step report);
            # lower against specs — the live buffers were just donated
            self._introspecting = True
            try:
                _introspect.capture_compile(
                    "whole_step", self._variant, fn,
                    (_specs(tws), _specs(frozen), _specs(states),
                     _specs(key), lrs, wds, ts, hyper,
                     *[_specs(x) for x in inputs]),
                    compile_seconds=compile_seconds)
            finally:
                self._introspecting = False
        if nmode != "off":
            self._numerics_boundary(
                loss_data,
                (tws, frozen, states, key, lrs, wds, ts, hyper, *inputs))
        # write results back into the live containers (the donated
        # buffers are dead; these are the fresh in-place outputs)
        for i, n, p in self._train_items:
            w = p.data()
            w._data = new_ws[n]
            w._version += 1
            _write_state(tr._states[i], new_states[n])
            # grads were consumed in-program: mark the (untouched) grad
            # buffers stale exactly like the legacy update bookkeeping
            tr._grad_versions[i] = p.grad()._version
        for p, v in zip(self._sink_params, aux):
            target = p.data() if isinstance(p, Parameter) else p
            target._data = v
            target._version += 1
        tr._record_step_complete(batch_size)
        return NDArray(loss_data)
