"""Activation layers (reference: python/mxnet/gluon/nn/activations.py)."""
from __future__ import annotations

from ... import numpy_extension as npx
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["LeakyReLU", "PReLU", "ELU", "SELU", "Swish", "SiLU", "GELU"]


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.leaky_relu(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"LeakyReLU({self._alpha})"


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1):
        super().__init__()
        from ... import initializer

        self.alpha = Parameter(
            "alpha", shape=(in_channels,),
            init=alpha_initializer or initializer.Constant(0.25))

    def forward(self, x):
        return npx.leaky_relu(x, self.alpha.data_for(x), act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.leaky_relu(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return npx.leaky_relu(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf"):
        super().__init__()
        self._approx = approximation

    def forward(self, x):
        act = "gelu" if self._approx == "erf" else "gelu_tanh"
        return npx.activation(x, act)


class Swish(HybridBlock):
    def __init__(self, beta=1.0):
        super().__init__()
        self._beta = beta

    def forward(self, x):
        if self._beta == 1.0:
            return npx.activation(x, "silu")
        return x * npx.activation(x * self._beta, "sigmoid")


SiLU = Swish
