"""Convolution, pooling and padding layers
(reference: python/mxnet/gluon/nn/conv_layers.py).

Layouts: channels-first (NCW/NCHW/NCDHW, the reference default) and
channels-last (NWC/NHWC/NDHWC — the TPU-preferred layout: C rides the lane
dimension so convs feed the MXU without transposes)."""
from __future__ import annotations

import numpy as _np

from ... import numpy_extension as npx
from ...ndarray.ndarray import apply_op
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, use_bias, in_channels, activation,
                 weight_initializer, bias_initializer, ndim, transpose=False,
                 output_padding=0, layout=None):
        super().__init__()
        self._channels = channels
        self._ndim = ndim
        self._kernel = _tup(kernel_size, ndim)
        self._strides = _tup(strides, ndim)
        self._padding = _tup(padding, ndim)
        self._dilation = _tup(dilation, ndim)
        self._groups = groups
        self._activation = activation
        self._transpose = transpose
        self._output_padding = _tup(output_padding, ndim)
        self._layout = layout
        self._channels_last = layout is not None and layout[-1] == "C"
        self.weight = Parameter("weight",
                                shape=self._weight_shape(in_channels),
                                init=weight_initializer,
                                allow_deferred_init=True)
        self.bias = (Parameter("bias", shape=(channels,),
                               init=bias_initializer or "zeros")
                     if use_bias else None)

    def _weight_shape(self, in_channels):
        c_in = in_channels // self._groups if in_channels else 0
        if self._transpose:
            # reference deconvolution weight: (I, O/g, *k) chan-first,
            # (I, *k, O/g) chan-last
            o = self._channels // self._groups
            if self._channels_last:
                return (in_channels,) + self._kernel + (o,)
            return (in_channels, o) + self._kernel
        if self._channels_last:
            return (self._channels,) + self._kernel + (c_in,)
        return (self._channels, c_in) + self._kernel

    def forward(self, x):
        c_in = x.shape[-1 if self._channels_last else 1]
        if self.weight._is_deferred:
            self.weight._finish_deferred_init(self._weight_shape(c_in))
        w = self.weight.data_for(x)
        b = self.bias.data_for(x) if self.bias is not None else None
        args = (x, w) if b is None else (x, w, b)
        if self._transpose:
            out = npx.deconvolution(
                *args, stride=self._strides, pad=self._padding,
                dilate=self._dilation, output_padding=self._output_padding,
                groups=self._groups, layout=self._layout)
        else:
            kernel_layout = None
            if getattr(self.weight, "_layout_perm", None) is not None:
                # weight buffers live in a persistently re-laid-out
                # physical shape (passes/layout.py); tell the op which
                # spec the bytes actually are so dn stays consistent
                sp = "DHW"[-self._ndim:]
                spec = ("O" + sp + "I") if self._channels_last \
                    else ("OI" + sp)
                kernel_layout = "".join(
                    spec[i] for i in self.weight._layout_perm)
            out = npx.convolution(
                *args, stride=self._strides, pad=self._padding,
                dilate=self._dilation, groups=self._groups,
                layout=self._layout, kernel_layout=kernel_layout)
        if self._activation:
            out = npx.activation(out, self._activation)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kernel}, stride={self._strides})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 1,
                         layout=layout)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 2,
                         layout=layout)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 3,
                         layout=layout)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 1,
                         transpose=True, output_padding=output_padding,
                         layout=layout)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 2,
                         transpose=True, output_padding=output_padding,
                         layout=layout)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 3,
                         transpose=True, output_padding=output_padding,
                         layout=layout)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, ndim, pool_type,
                 global_pool=False, count_include_pad=True, ceil_mode=False,
                 layout=None):
        super().__init__()
        self._kernel = _tup(pool_size, ndim)
        self._strides = _tup(strides if strides is not None else pool_size,
                             ndim)
        self._padding = _tup(padding, ndim)
        self._pool_type = pool_type
        self._global = global_pool
        self._count_include_pad = count_include_pad
        self._layout = layout
        self._ceil_mode = bool(ceil_mode)

    def forward(self, x):
        return npx.pooling(
            x, kernel=self._kernel, pool_type=self._pool_type,
            stride=self._strides, pad=self._padding,
            global_pool=self._global,
            count_include_pad=self._count_include_pad,
            layout=self._layout, ceil_mode=self._ceil_mode)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class MaxPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(pool_size, strides, padding, 1, "max",
                         ceil_mode=ceil_mode, layout=layout)


class MaxPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(pool_size, strides, padding, 2, "max",
                         ceil_mode=ceil_mode, layout=layout)


class MaxPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(pool_size, strides, padding, 3, "max",
                         ceil_mode=ceil_mode, layout=layout)


class AvgPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(pool_size, strides, padding, 1, "avg",
                         count_include_pad=count_include_pad,
                         ceil_mode=ceil_mode, layout=layout)


class AvgPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(pool_size, strides, padding, 2, "avg",
                         count_include_pad=count_include_pad,
                         ceil_mode=ceil_mode, layout=layout)


class AvgPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(pool_size, strides, padding, 3, "avg",
                         count_include_pad=count_include_pad,
                         ceil_mode=ceil_mode, layout=layout)


class _GlobalPool(_Pool):
    def __init__(self, ndim, pool_type, layout=None):
        super().__init__(1, 1, 0, ndim, pool_type, global_pool=True,
                         layout=layout)


class GlobalMaxPool1D(_GlobalPool):
    def __init__(self, layout="NCW"):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(1, "max", layout=layout)


class GlobalMaxPool2D(_GlobalPool):
    def __init__(self, layout="NCHW"):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(2, "max", layout=layout)


class GlobalMaxPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW"):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(3, "max", layout=layout)


class GlobalAvgPool1D(_GlobalPool):
    def __init__(self, layout="NCW"):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(1, "avg", layout=layout)


class GlobalAvgPool2D(_GlobalPool):
    def __init__(self, layout="NCHW"):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(2, "avg", layout=layout)


class GlobalAvgPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW"):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(3, "avg", layout=layout)


class ReflectionPad2D(HybridBlock):
    """Reflection padding (reference: nn.ReflectionPad2D)."""

    def __init__(self, padding=0):
        super().__init__()
        self._padding = _tup(padding, 2)

    def forward(self, x):
        import jax.numpy as jnp

        ph, pw = self._padding
        return apply_op(
            lambda v: jnp.pad(
                v, ((0, 0), (0, 0), (ph, ph), (pw, pw)), mode="reflect"), x)


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim):
        super().__init__()
        self._factor = _tup(factor, ndim)
        self._ndim = ndim

    def __repr__(self):
        return f"{type(self).__name__}({self._factor})"


class PixelShuffle1D(_PixelShuffle):
    """(N, C*f, W) -> (N, C, W*f) sub-pixel upsample (reference:
    nn.PixelShuffle1D, conv_layers.py:1707)."""

    def __init__(self, factor):
        super().__init__(factor, 1)

    def forward(self, x):
        (f,) = self._factor

        def pure(v):
            n, cf, w = v.shape
            c = cf // f
            return v.reshape(n, c, f, w).transpose(0, 1, 3, 2) \
                .reshape(n, c, w * f)

        return apply_op(pure, x)


class PixelShuffle2D(_PixelShuffle):
    """(N, C*fh*fw, H, W) -> (N, C, H*fh, W*fw) (reference:
    nn.PixelShuffle2D, conv_layers.py:1755)."""

    def __init__(self, factor):
        super().__init__(factor, 2)

    def forward(self, x):
        fh, fw = self._factor

        def pure(v):
            n, cff, h, w = v.shape
            c = cff // (fh * fw)
            return v.reshape(n, c, fh, fw, h, w) \
                .transpose(0, 1, 4, 2, 5, 3) \
                .reshape(n, c, h * fh, w * fw)

        return apply_op(pure, x)


class PixelShuffle3D(_PixelShuffle):
    """(N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3) (reference:
    nn.PixelShuffle3D, conv_layers.py:1818)."""

    def __init__(self, factor):
        super().__init__(factor, 3)

    def forward(self, x):
        f1, f2, f3 = self._factor

        def pure(v):
            n, cf, d, h, w = v.shape
            c = cf // (f1 * f2 * f3)
            return v.reshape(n, c, f1, f2, f3, d, h, w) \
                .transpose(0, 1, 5, 2, 6, 3, 7, 4) \
                .reshape(n, c, d * f1, h * f2, w * f3)

        return apply_op(pure, x)


class DeformableConvolution(HybridBlock):
    """DCNv1 layer: a regular conv branch producing offsets + the
    deformable conv itself (reference: nn.DeformableConvolution,
    conv_layers.py:1277; op contrib/deformable_convolution.cc)."""

    _use_mask = False

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None, weight_initializer=None,
                 bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True):
        super().__init__()
        assert layout == "NCHW", "deformable conv is NCHW-only"
        self._channels = channels
        self._kernel = _tup(kernel_size, 2)
        self._strides = _tup(strides, 2)
        self._padding = _tup(padding, 2)
        self._dilation = _tup(dilation, 2)
        self._groups = groups
        self._ndg = num_deformable_group
        self._activation = activation
        kh, kw = self._kernel
        mult = 3 if self._use_mask else 2
        self.offset_conv = Conv2D(
            mult * num_deformable_group * kh * kw, self._kernel,
            self._strides, self._padding, self._dilation,
            use_bias=offset_use_bias, in_channels=in_channels,
            weight_initializer=offset_weight_initializer,
            bias_initializer=offset_bias_initializer)
        self.weight = Parameter(
            "weight",
            shape=(channels, in_channels // groups if in_channels else 0,
                   kh, kw),
            init=weight_initializer, allow_deferred_init=True)
        self.bias = (Parameter("bias", shape=(channels,),
                               init=bias_initializer)
                     if use_bias else None)

    def forward(self, x):
        from ...ops import vision as _vision

        c_in = x.shape[1]
        if self.weight._is_deferred:
            kh, kw = self._kernel
            self.weight._finish_deferred_init(
                (self._channels, c_in // self._groups, kh, kw))
        offs = self.offset_conv(x)
        kh, kw = self._kernel
        if self._use_mask:
            n_off = 2 * self._ndg * kh * kw
            offset, m = offs[:, :n_off], offs[:, n_off:]
            import jax

            m = apply_op(jax.nn.sigmoid, m)
        else:
            offset, m = offs, None
        w = self.weight.data_for(x)
        b = self.bias.data_for(x) if self.bias is not None else None

        def pure(xv, ov, wv, *rest):
            i = 0
            bv = mv = None
            if b is not None:
                bv = rest[i]; i += 1
            if m is not None:
                mv = rest[i]; i += 1
            return _vision.deformable_convolution(
                xv, ov, wv, bias=bv, kernel=self._kernel,
                stride=self._strides, pad=self._padding,
                dilate=self._dilation, num_deformable_group=self._ndg,
                groups=self._groups, mask=mv)

        extra = [a for a in (b, m) if a is not None]
        out = apply_op(pure, x, offset, w, *extra)
        if self._activation:
            out = npx.activation(out, self._activation)
        return out


class ModulatedDeformableConvolution(DeformableConvolution):
    """DCNv2: offsets + sigmoid-modulated sample masks (reference:
    nn.ModulatedDeformableConvolution, conv_layers.py:1501)."""

    _use_mask = True


__all__ += ["PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D",
            "DeformableConvolution", "ModulatedDeformableConvolution"]
