"""Convolution, pooling and padding layers
(reference: python/mxnet/gluon/nn/conv_layers.py).

Layouts: channels-first (NCW/NCHW/NCDHW, the reference default) and
channels-last (NWC/NHWC/NDHWC — the TPU-preferred layout: C rides the lane
dimension so convs feed the MXU without transposes)."""
from __future__ import annotations

import numpy as _np

from ... import numpy_extension as npx
from ...ndarray.ndarray import apply_op
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, use_bias, in_channels, activation,
                 weight_initializer, bias_initializer, ndim, transpose=False,
                 output_padding=0, layout=None):
        super().__init__()
        self._channels = channels
        self._ndim = ndim
        self._kernel = _tup(kernel_size, ndim)
        self._strides = _tup(strides, ndim)
        self._padding = _tup(padding, ndim)
        self._dilation = _tup(dilation, ndim)
        self._groups = groups
        self._activation = activation
        self._transpose = transpose
        self._output_padding = _tup(output_padding, ndim)
        self._layout = layout
        self._channels_last = layout is not None and layout[-1] == "C"
        self.weight = Parameter("weight",
                                shape=self._weight_shape(in_channels),
                                init=weight_initializer,
                                allow_deferred_init=True)
        self.bias = (Parameter("bias", shape=(channels,),
                               init=bias_initializer or "zeros")
                     if use_bias else None)

    def _weight_shape(self, in_channels):
        c_in = in_channels // self._groups if in_channels else 0
        if self._transpose:
            # reference deconvolution weight: (I, O/g, *k) chan-first,
            # (I, *k, O/g) chan-last
            o = self._channels // self._groups
            if self._channels_last:
                return (in_channels,) + self._kernel + (o,)
            return (in_channels, o) + self._kernel
        if self._channels_last:
            return (self._channels,) + self._kernel + (c_in,)
        return (self._channels, c_in) + self._kernel

    def forward(self, x):
        c_in = x.shape[-1 if self._channels_last else 1]
        if self.weight._is_deferred:
            self.weight._finish_deferred_init(self._weight_shape(c_in))
        w = self.weight.data_for(x)
        b = self.bias.data_for(x) if self.bias is not None else None
        args = (x, w) if b is None else (x, w, b)
        if self._transpose:
            out = npx.deconvolution(
                *args, stride=self._strides, pad=self._padding,
                dilate=self._dilation, output_padding=self._output_padding,
                groups=self._groups, layout=self._layout)
        else:
            out = npx.convolution(
                *args, stride=self._strides, pad=self._padding,
                dilate=self._dilation, groups=self._groups,
                layout=self._layout)
        if self._activation:
            out = npx.activation(out, self._activation)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kernel}, stride={self._strides})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 1,
                         layout=layout)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 2,
                         layout=layout)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 3,
                         layout=layout)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 1,
                         transpose=True, output_padding=output_padding,
                         layout=layout)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 2,
                         transpose=True, output_padding=output_padding,
                         layout=layout)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, use_bias, in_channels, activation,
                         weight_initializer, bias_initializer, 3,
                         transpose=True, output_padding=output_padding,
                         layout=layout)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, ndim, pool_type,
                 global_pool=False, count_include_pad=True, ceil_mode=False,
                 layout=None):
        super().__init__()
        self._kernel = _tup(pool_size, ndim)
        self._strides = _tup(strides if strides is not None else pool_size,
                             ndim)
        self._padding = _tup(padding, ndim)
        self._pool_type = pool_type
        self._global = global_pool
        self._count_include_pad = count_include_pad
        self._layout = layout
        if ceil_mode:
            raise NotImplementedError("ceil_mode pooling not supported")

    def forward(self, x):
        return npx.pooling(
            x, kernel=self._kernel, pool_type=self._pool_type,
            stride=self._strides, pad=self._padding,
            global_pool=self._global,
            count_include_pad=self._count_include_pad,
            layout=self._layout)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class MaxPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(pool_size, strides, padding, 1, "max",
                         ceil_mode=ceil_mode, layout=layout)


class MaxPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(pool_size, strides, padding, 2, "max",
                         ceil_mode=ceil_mode, layout=layout)


class MaxPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(pool_size, strides, padding, 3, "max",
                         ceil_mode=ceil_mode, layout=layout)


class AvgPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(pool_size, strides, padding, 1, "avg",
                         count_include_pad=count_include_pad,
                         ceil_mode=ceil_mode, layout=layout)


class AvgPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(pool_size, strides, padding, 2, "avg",
                         count_include_pad=count_include_pad,
                         ceil_mode=ceil_mode, layout=layout)


class AvgPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(pool_size, strides, padding, 3, "avg",
                         count_include_pad=count_include_pad,
                         ceil_mode=ceil_mode, layout=layout)


class _GlobalPool(_Pool):
    def __init__(self, ndim, pool_type, layout=None):
        super().__init__(1, 1, 0, ndim, pool_type, global_pool=True,
                         layout=layout)


class GlobalMaxPool1D(_GlobalPool):
    def __init__(self, layout="NCW"):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(1, "max", layout=layout)


class GlobalMaxPool2D(_GlobalPool):
    def __init__(self, layout="NCHW"):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(2, "max", layout=layout)


class GlobalMaxPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW"):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(3, "max", layout=layout)


class GlobalAvgPool1D(_GlobalPool):
    def __init__(self, layout="NCW"):
        assert layout in ("NCW", "NWC"), layout
        super().__init__(1, "avg", layout=layout)


class GlobalAvgPool2D(_GlobalPool):
    def __init__(self, layout="NCHW"):
        assert layout in ("NCHW", "NHWC"), layout
        super().__init__(2, "avg", layout=layout)


class GlobalAvgPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW"):
        assert layout in ("NCDHW", "NDHWC"), layout
        super().__init__(3, "avg", layout=layout)


class ReflectionPad2D(HybridBlock):
    """Reflection padding (reference: nn.ReflectionPad2D)."""

    def __init__(self, padding=0):
        super().__init__()
        self._padding = _tup(padding, 2)

    def forward(self, x):
        import jax.numpy as jnp

        ph, pw = self._padding
        return apply_op(
            lambda v: jnp.pad(
                v, ((0, 0), (0, 0), (ph, ph), (pw, pw)), mode="reflect"), x)
