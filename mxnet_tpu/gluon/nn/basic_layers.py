"""Basic layers (reference: python/mxnet/gluon/nn/basic_layers.py).

Every layer's forward is pure NDArray->NDArray through the npx/apply_op path,
so the same code runs eagerly (taped) and under CachedOp tracing (jit).
Deferred init: unknown input dims (0) are inferred on first forward.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ... import autograd as ag
from ... import numpy_extension as npx
from ...ndarray.ndarray import NDArray, apply_op
from ...ops import nn as _nn
from ..block import Block, HybridBlock, current_state_sink
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "SyncBatchNorm", "BatchNormReLU", "LayerNorm", "GroupNorm", "InstanceNorm",
           "Embedding", "Flatten", "Lambda", "HybridLambda", "Concatenate",
           "HybridConcatenate", "Identity", "Activation", "HybridBlock"]


class Sequential(Block):
    """Sequential container (reference: nn.Sequential)."""

    def __init__(self, *blocks):
        super().__init__()
        for b in blocks:
            self.add(b)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __getitem__(self, key):
        children = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*children[key])
            return net
        return children[key]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Sequential that compiles as ONE jit program when hybridized
    (reference: nn.HybridSequential)."""

    def __init__(self, *blocks):
        super().__init__()
        for b in blocks:
            self.add(b)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __getitem__(self, key):
        children = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*children[key])
            return net
        return children[key]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully connected layer (reference: nn.Dense; op FullyConnected)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0):
        super().__init__()
        self._units = units
        self._flatten = flatten
        self._activation = activation
        self.weight = Parameter("weight", shape=(units, in_units),
                                dtype=dtype, init=weight_initializer,
                                allow_deferred_init=True)
        self.bias = (
            Parameter("bias", shape=(units,), dtype=dtype,
                      init=bias_initializer, allow_deferred_init=True)
            if use_bias else None
        )

    def forward(self, x):
        if self.weight._is_deferred:
            in_units = (
                int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1])
            self.weight._finish_deferred_init((self._units, in_units))
        if self.bias is not None and self.bias._is_deferred:
            self.bias._finish_deferred_init((self._units,))
        w = self.weight.data_for(x)
        b = self.bias.data_for(x) if self.bias is not None else None
        if b is None:
            out = npx.fully_connected(x, w, flatten=self._flatten)
        else:
            out = npx.fully_connected(x, w, b, flatten=self._flatten)
        if self._activation is not None:
            out = npx.activation(out, self._activation)
        return out

    def __repr__(self):
        return f"Dense({self._units}, in_units={self.weight.shape[1]})"


class Dropout(HybridBlock):
    """Dropout (reference: nn.Dropout)."""

    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        if self._rate <= 0:
            return x
        return npx.dropout(x, p=self._rate, axes=self._axes or None)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with moving stats (reference: nn.BatchNorm).

    Running-stat updates go through the trace state sink when compiled (the
    mutable-aux-input analog of nn/batch_norm.cc) and mutate eagerly
    otherwise.
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):  # noqa: ARG002
        super().__init__()
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        sh = (in_channels,)
        self.gamma = Parameter("gamma", shape=sh,
                               init=gamma_initializer,
                               allow_deferred_init=True,
                               differentiable=scale)
        self.beta = Parameter("beta", shape=sh, init=beta_initializer,
                              allow_deferred_init=True,
                              differentiable=center)
        self.running_mean = Parameter("running_mean", shape=sh,
                                      init=running_mean_initializer,
                                      grad_req="null",
                                      differentiable=False,
                                      allow_deferred_init=True)
        self.running_var = Parameter("running_var", shape=sh,
                                     init=running_variance_initializer,
                                     grad_req="null",
                                     differentiable=False,
                                     allow_deferred_init=True)

    def _defer(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            if p._is_deferred:
                p._finish_deferred_init((c,))

    def forward(self, x):
        self._defer(x)
        gamma = self.gamma.data_for(x)
        beta = self.beta.data_for(x)
        rmean = self.running_mean.data_for(x)
        rvar = self.running_var.data_for(x)
        if not self._scale:
            gamma = apply_op(jnp.ones_like, gamma)
        training = ag.is_training() and not self._use_global_stats
        out, nm, nv = apply_op(
            lambda a, g, b, m, v: _nn.batch_norm(
                a, g, b, m, v, eps=self._epsilon, momentum=self._momentum,
                training=training, use_global_stats=self._use_global_stats,
                axis=self._axis),
            x, gamma, beta, rmean, rvar, name="BatchNorm")
        if training:
            sink = current_state_sink()
            if sink is not None:
                sink.record(self.running_mean, nm._data)
                sink.record(self.running_var, nv._data)
            else:
                self.running_mean.data_for(x)._assign_from(nm.detach())
                self.running_var.data_for(x)._assign_from(nv.detach())
        return out

    def __repr__(self):
        return (f"BatchNorm(axis={self._axis}, momentum={self._momentum}, "
                f"in_channels={self.gamma.shape[0]})")


class BatchNormReLU(BatchNorm):
    """Fused BatchNorm + ReLU (reference: nn.BatchNormReLU,
    basic_layers.py:478; op contrib/batch_norm_relu.cc). On TPU the fusion
    is XLA's job — the layer exists for API parity."""

    def forward(self, x):
        out = super().forward(x)
        return apply_op(lambda v: jnp.maximum(v, 0), out, name="relu")


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: contrib SyncBatchNorm).

    Under the sharded trainer, batch stats are computed over the global batch
    automatically by XLA SPMD; as a standalone layer it equals BatchNorm."""

    def __init__(self, in_channels=0, num_devices=None, **kwargs):  # noqa: ARG002
        super().__init__(in_channels=in_channels, **kwargs)


class LayerNorm(HybridBlock):
    """Layer normalization (reference: nn.LayerNorm)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, dtype="float32"):
        super().__init__()
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer, dtype=dtype,
                               allow_deferred_init=True,
                               differentiable=scale)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer, dtype=dtype,
                              allow_deferred_init=True,
                              differentiable=center)

    def forward(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p._is_deferred:
                p._finish_deferred_init((c,))
        return npx.layer_norm(x, self.gamma.data_for(x),
                              self.beta.data_for(x), axis=self._axis,
                              eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Group normalization (reference: nn.GroupNorm)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer,
                               allow_deferred_init=True,
                               differentiable=scale)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer,
                              allow_deferred_init=True,
                              differentiable=center)

    def forward(self, x):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if p._is_deferred:
                p._finish_deferred_init((c,))
        return npx.group_norm(x, self.gamma.data_for(x),
                              self.beta.data_for(x),
                              num_groups=self._num_groups,
                              eps=self._epsilon)


class InstanceNorm(HybridBlock):
    """Instance normalization (reference: nn.InstanceNorm)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0):  # noqa: ARG002
        super().__init__()
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer,
                               allow_deferred_init=True,
                               differentiable=scale)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer,
                              allow_deferred_init=True,
                              differentiable=center)

    def forward(self, x):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if p._is_deferred:
                p._finish_deferred_init((c,))
        return npx.instance_norm(x, self.gamma.data_for(x),
                                 self.beta.data_for(x), eps=self._epsilon)


class Embedding(HybridBlock):
    """Embedding lookup (reference: nn.Embedding).

    sparse_grad=True: the tape's grad accumulation stays a dense XLA
    scatter-add (the efficient TPU form), but the forward records the
    touched row ids on the Parameter, so the Trainer hands the optimizer a
    RowSparseNDArray and the lazy_update path touches ONLY those rows
    (reference: nn.Embedding sparse_grad + optimizer/sgd.py:36-95)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False):
        super().__init__()
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = Parameter(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer,
            grad_stype="row_sparse" if sparse_grad else "default")

    def forward(self, x):
        if self._sparse_grad:
            data = x._data if hasattr(x, "_data") else x
            self.weight._record_sparse_rows(data)
        return npx.embedding(x, self.weight.data_for(x))

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    """Flatten to (N, -1) (reference: nn.Flatten)."""

    def forward(self, x):
        return x.reshape((x.shape[0], -1))

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def forward(self, x):
        return x


def _resolve_lambda(function):
    """A string names an operator (reference: nn.Lambda accepts
    'tanh' → mx.nd.tanh / F.tanh); search npx, then np, then nd."""
    if not isinstance(function, str):
        if not callable(function):
            raise ValueError(
                f"Lambda expects a callable or an operator name string, "
                f"got {type(function)}")
        return function
    from ... import ndarray as _nd
    from ... import numpy as _mnp
    from ... import numpy_extension as _npx

    for ns in (_npx, _mnp, _nd):
        fn = getattr(ns, function, None)
        if callable(fn):
            return fn
    raise ValueError(f"no operator named {function!r} in npx/np/nd")


class Lambda(Block):
    """Wrap a function (or op-name string) as a layer (reference:
    nn.Lambda)."""

    def __init__(self, function):
        super().__init__()
        self._func = _resolve_lambda(function)

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function):
        super().__init__()
        self._func = _resolve_lambda(function)

    def forward(self, *args):
        return self._func(*args)


class Concatenate(Sequential):
    """Run children on the same input, concat outputs (reference:
    contrib Concurrent / nn.Concatenate)."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        from ... import numpy as np

        outs = [block(x) for block in self._children.values()]
        return np.concatenate(outs, axis=self._axis)


class HybridConcatenate(HybridSequential):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        from ... import numpy as np

        outs = [block(x) for block in self._children.values()]
        return np.concatenate(outs, axis=self._axis)


class Activation(HybridBlock):
    """Activation layer (reference: nn.Activation)."""

    def __init__(self, activation):
        super().__init__()
        self._act = activation

    def forward(self, x):
        return npx.activation(x, self._act)

    def __repr__(self):
        return f"Activation({self._act})"
