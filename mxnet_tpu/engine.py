"""Engine shim: async-dispatch semantics over XLA/PJRT.

The reference's ThreadedEngine (src/engine/threaded_engine*.cc) is a
dependency scheduler that makes every op asynchronous and serializes
conflicting reads/writes on versioned variables. On TPU the same public
semantics fall out of JAX's asynchronous dispatch: every eager op is enqueued
on the device stream and python returns immediately; data dependencies are
tracked by XLA/PJRT itself (each jax.Array *is* the versioned variable — our
NDArray swaps in a fresh jax.Array on every mutation, which is exactly the
reference's `ThreadedVar::version_` bump).

What remains for this layer to provide, and does:
  * `waitall()` — block until all outstanding work is done
    (reference: Engine::WaitForAll, used by MXNDArrayWaitAll).
  * `wait_to_read(arr)` — per-array sync (reference: NDArray::WaitToRead).
  * deferred exception surfacing — XLA raises device-side errors at the
    first sync point, matching the reference's per-var exception_ptr rethrow
    (src/engine/threaded_engine.cc:440-530).
  * an engine-type switch for debugging: `naive` mode makes every op
    synchronous, the analog of MXNET_ENGINE_TYPE=NaiveEngine
    (src/engine/engine.cc:32-56).
  * bulking knobs exist in the reference to batch engine pushes
    (MXNET_EXEC_BULK_EXEC_*); under XLA whole subgraphs are fused by jit, so
    `set_bulk_size` is kept as an accepted no-op for API parity.
"""
from __future__ import annotations

import os
import time
import weakref

import jax

from .diagnostics import spans as _spans
from .diagnostics import watchdog as _watchdog
from .telemetry import instruments as _telemetry

__all__ = ["waitall", "wait_to_read", "set_bulk_size", "bulk", "engine_type",
           "push", "new_var", "wait_for_var", "native_engine"]

# Weak set of live NDArrays handed out by this framework; waitall() blocks on
# the ones still alive. Arrays that died were either donated or their work is
# transitively depended on by live ones.
_live = weakref.WeakSet()

# MXNET_ENGINE_TYPE parity: 'ThreadedEnginePerDevice' (default, async) or
# 'NaiveEngine' (synchronous eager dispatch, for deterministic debugging).
from . import env as _env

_engine_type = _env.get("MXNET_ENGINE_TYPE")


def engine_type():
    return _engine_type


def is_naive():
    return _engine_type == "NaiveEngine"


def track(arr):
    _live.add(arr)
    return arr


def waitall():
    """Block until all outstanding device work has completed.

    Device-side failures deferred by async dispatch are raised here, matching
    the reference's WaitForAll exception rethrow semantics. Also drains the
    native host engine (engine-pushed IO/compute tasks).
    """
    t0 = time.perf_counter()
    with _spans.span("waitall", cat="sync"), _watchdog.guard("waitall"):
        for arr in list(_live):
            data = getattr(arr, "_data", None)
            if data is not None and hasattr(data, "block_until_ready"):
                data.block_until_ready()
        eng = native_engine()
        if eng is not None:
            eng.wait_all()
            from ._checkpoint_io import reap_idle

            reap_idle()  # all IO drained: drop per-path bookkeeping
    _telemetry.record_sync("waitall", time.perf_counter() - t0)


def native_engine():
    """The C++ dependency engine singleton (None without native lib).

    Device compute is scheduled by XLA/PJRT; this engine schedules *host*
    work pushed with read/write variable sets — data-pipeline stages,
    checkpoint IO, custom host ops — with the reference's semantics
    (versioned vars, conflicting-access serialization, deferred
    exceptions; native/mxtpu_runtime.cc; reference
    src/engine/threaded_engine.{h,cc}).
    """
    from . import _native

    return _native.engine()


def new_var():
    """Allocate an engine variable (reference: Engine::NewVariable)."""
    eng = native_engine()
    if eng is None:
        raise RuntimeError("native engine unavailable")
    return eng.new_var()


def push(fn, const_vars=(), mutable_vars=(), priority=0, io=False):
    """Push an async host op with dependencies (Engine::PushAsync).

    In NaiveEngine mode the op runs synchronously on the calling thread
    (reference: naive_engine.cc — deterministic debugging)."""
    if is_naive():
        fn()
        return
    eng = native_engine()
    if eng is None:
        fn()
        return
    eng.push(fn, const_vars, mutable_vars, priority, io)


def wait_for_var(var):
    """Block until all ops touching `var` completed; rethrows deferred
    exceptions attached to it (reference: Engine::WaitForVar +
    ThrowException, threaded_engine.cc:520)."""
    eng = native_engine()
    if eng is not None:
        eng.wait_for_var(var)


def wait_to_read(arr):
    data = getattr(arr, "_data", arr)
    if hasattr(data, "block_until_ready"):
        t0 = time.perf_counter()
        with _spans.span("wait_to_read", cat="sync"), \
                _watchdog.guard("wait_to_read"):
            data.block_until_ready()
        _telemetry.record_sync("wait_to_read", time.perf_counter() - t0)


_bulk_size = 15


def set_bulk_size(size):
    """Parity no-op: XLA jit fusion subsumes engine op-bulking.

    Returns the previous value like the reference (engine.h:430).
    """
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


class bulk:
    """Context manager parity with mx.engine.bulk (no-op under XLA)."""

    def __init__(self, size):
        self._size = size

    def __enter__(self):
        self._prev = set_bulk_size(self._size)

    def __exit__(self, *exc):
        set_bulk_size(self._prev)
        return False
