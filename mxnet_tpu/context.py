"""Legacy context module (reference: python/mxnet/context.py — kept as an
alias layer over device.py in 2.x). `Context` is `Device`."""
from .device import (  # noqa: F401
    Device,
    Device as Context,
    cpu,
    cpu_pinned,
    current_device,
    current_device as current_context,
    gpu,
    num_gpus,
    tpu,
)

__all__ = ["Context", "Device", "cpu", "cpu_pinned", "gpu", "tpu",
           "current_context", "current_device", "num_gpus"]
