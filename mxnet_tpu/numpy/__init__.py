"""mx.np — NumPy-compatible frontend (the Gluon-2.0 default array API).

Re-design of the reference's `python/mxnet/numpy/` (multiarray.py 13k LoC of
generated `_npi_*` wrappers): instead of codegen over an NNVM registry, ops are
generated over `jax.numpy` by `multiarray._make_np_module`, with handwritten
creation/random/linalg where device placement or MXNet semantics differ.
Every function dispatches through `apply_op`, so it is taped under
autograd.record() and traceable under hybridize/jit.
"""
from . import linalg, random  # noqa: F401
from .multiarray import *  # noqa: F401,F403
from .multiarray import __all__, ndarray  # noqa: F401
