"""mx.np.linalg — linear algebra over jnp.linalg / XLA.

Reference: src/operator/numpy/linalg/ (`_npi_*` linalg ops backed by
LAPACK/cuSOLVER) and the `la_op` suite (potrf, gelqf, syrk...). On TPU these
lower to XLA's decomposition HLOs; MXU handles the inner gemms.

Return conventions follow the REFERENCE docstrings, not numpy's, wherever
the two differ (python/mxnet/numpy/linalg.py):
  * svd       -> gesvd convention ``(ut, s, v)`` with ``v: (..., M, N)``,
                 ``a = ut @ diag(s) @ v`` (linalg.py:729-752) — numpy's
                 *reduced* SVD, not the full_matrices default.
  * eigh/eigvalsh take ``upper=False`` (bool), not numpy's UPLO string
                 (linalg.py:1336,1466).
  * matrix_rank/pinv take ``rtol``/``hermitian`` per the array-api text
                 the reference adopted (linalg.py:35,510).
  * lstsq     accepts the reference default ``rcond='warn'``
                 (linalg.py:438) and returns numpy-style residuals.
  * eig/eigvals are real-in/real-out (reference: "Does not support
                 complex input and output", linalg.py:1398-1447) and run
                 on the host via pure_callback — the same LAPACK geev
                 call the reference makes, and TPU-safe under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import apply_op

_FNS = """
cholesky det slogdet eig eigh eigvals eigvalsh inv lstsq matrix_power
matrix_rank norm pinv qr solve svd svdvals tensorinv tensorsolve cond
multi_dot matrix_norm vector_norm cross outer matmul trace diagonal
""".split()

__all__ = list(_FNS)


def _wrap_fn(name, jfn):
    """NDArray plumbing around a pure jnp-level function: concrete
    NDArrays go through apply_op (engine var tracking); tracers and raw
    arrays call straight through."""

    def fn(*args, **kwargs):
        from ..ndarray.ndarray import NDArray

        # find NDArrays anywhere in the args tree (multi_dot takes a LIST
        # of matrices, so a flat positional scan misses them)
        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, NDArray))
        nd_idx = [i for i, l in enumerate(leaves)
                  if isinstance(l, NDArray)]
        if not nd_idx:
            out = jfn(*args, **kwargs)
            if isinstance(out, tuple):
                return tuple(NDArray(o) for o in out)
            return NDArray(out)

        def pure(*xs):
            filled = list(leaves)
            for i, x in zip(nd_idx, xs):
                filled[i] = x
            call_args, call_kwargs = jax.tree_util.tree_unflatten(
                treedef, filled)
            out = jfn(*call_args, **call_kwargs)
            return tuple(out) if isinstance(out, tuple) else out

        return apply_op(pure, *[leaves[i] for i in nd_idx],
                        name=f"linalg.{name}")

    fn.__name__ = name
    return fn


def _wrap(name):
    return _wrap_fn(name, getattr(jnp.linalg, name))


for _name in _FNS:
    if hasattr(jnp.linalg, _name):
        globals()[_name] = _wrap(_name)


# -- reference-convention overrides (see module docstring; pure impls
# shared with the _npi_* op registry so graph-mode execution matches) ----

from ..ops import np_linalg as _np_linalg  # noqa: E402

for _name in _np_linalg.__all__:
    globals()[_name] = _wrap_fn(_name, getattr(_np_linalg, _name))
