"""mx.np.linalg — linear algebra over jnp.linalg / XLA.

Reference: src/operator/numpy/linalg/ (`_npi_*` linalg ops backed by
LAPACK/cuSOLVER) and the `la_op` suite (potrf, gelqf, syrk...). On TPU these
lower to XLA's decomposition HLOs; MXU handles the inner gemms.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray.ndarray import apply_op

_FNS = """
cholesky det slogdet eig eigh eigvals eigvalsh inv lstsq matrix_power
matrix_rank norm pinv qr solve svd svdvals tensorinv tensorsolve cond
multi_dot matrix_norm vector_norm cross outer matmul trace diagonal
""".split()

__all__ = list(_FNS)


def _wrap(name):
    jfn = getattr(jnp.linalg, name)

    def fn(*args, **kwargs):
        from ..ndarray.ndarray import NDArray

        nd_args = [a for a in args if isinstance(a, NDArray)]
        if not nd_args:
            out = jfn(*args, **kwargs)
            if isinstance(out, tuple):
                return tuple(NDArray(o) for o in out)
            return NDArray(out)

        def pure(*xs):
            it = iter(xs)
            call = [next(it) if isinstance(a, NDArray) else a for a in args]
            out = jfn(*call, **kwargs)
            return tuple(out) if isinstance(out, tuple) else out

        return apply_op(pure, *nd_args, name=f"linalg.{name}")

    fn.__name__ = name
    return fn


for _name in _FNS:
    if hasattr(jnp.linalg, _name):
        globals()[_name] = _wrap(_name)
