"""mx.np function corpus.

Generated wrappers over jax.numpy (see _UNARY/_BINARY/_REDUCE/_OTHER lists)
plus handwritten creation ops honoring the current Device, mirroring the
reference's `python/mxnet/numpy/multiarray.py` + function_base/creation
namespaces (139 `_npi_*` C++ ops, SURVEY.md §2.2).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import normalize_dtype
from ..device import Device, current_device
from ..ndarray.ndarray import NDArray, apply_op
from ..ndarray.ndarray import array as _nd_array

ndarray = NDArray

pi = _np.pi
e = _np.e
euler_gamma = _np.euler_gamma
inf = _np.inf
nan = _np.nan
newaxis = None

_DTYPE_KW = ("dtype",)


def _fix_kwargs(kwargs):
    if "ctx" in kwargs:
        kwargs.pop("ctx")
    if "device" in kwargs:
        kwargs.pop("device")
    if "out" in kwargs and kwargs["out"] is None:
        kwargs.pop("out")
    if "dtype" in kwargs:
        kwargs["dtype"] = normalize_dtype(kwargs["dtype"])
    return kwargs


def _call_listok(jnp_fn, call_args, call_kwargs):
    """Call jnp_fn; if it rejects a plain Python list operand (jnp is
    stricter than numpy/the reference: np.percentile(a, [10, 90]),
    np.insert(x, [1, 4], vals) are legal there), convert list args to
    numpy arrays and retry once."""
    def _plain_list(a):
        # only lists of plain python/numpy scalars (possibly nested) are
        # safe to convert — a list holding a traced array must pass
        # through untouched or _np.asarray would raise/devalue it.
        # builtins.all: this module's generated `all` shadows the builtin
        # with mx.np.all, which rejects generators.
        import builtins

        if not isinstance(a, list):
            return False
        return builtins.all(
            isinstance(v, (int, float, bool, complex, _np.number))
            or _plain_list(v) for v in a)

    try:
        return jnp_fn(*call_args, **call_kwargs)
    except TypeError:
        # retry with list operands converted whenever any are present —
        # matching on jax's exact message ("requires ndarray or scalar")
        # would silently disable list support if a jax upgrade rewords it
        import builtins  # `all` is shadowed by the generated mx.np.all

        conv = [_np.asarray(a) if _plain_list(a) else a
                for a in call_args]
        kconv = {k: _np.asarray(v) if _plain_list(v) else v
                 for k, v in call_kwargs.items()}
        if builtins.all(c is a for c, a in zip(conv, call_args)) \
                and builtins.all(kconv[k] is call_kwargs[k]
                                 for k in kconv):
            raise  # nothing convertible: the TypeError is genuine
        return jnp_fn(*conv, **kconv)


def _wrap_jnp(jnp_fn):
    """Make an mx.np function from a jnp function.

    Every NDArray — positional, keyword, OR nested inside a tuple/list
    argument (ravel_multi_index takes a tuple of index arrays) — routes
    through apply_op, so gradients flow regardless of spelling."""

    @functools.wraps(jnp_fn)
    def wrapped(*args, **kwargs):
        kwargs = _fix_kwargs(dict(kwargs))
        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, NDArray))
        nd_idx = [i for i, l in enumerate(leaves)
                  if isinstance(l, NDArray)]

        def fn(*xs):
            filled = list(leaves)
            for i, x in zip(nd_idx, xs):
                filled[i] = x
            call_args, call_kwargs = jax.tree_util.tree_unflatten(
                treedef, filled)
            return _call_listok(jnp_fn, call_args, call_kwargs)

        return apply_op(fn, *[leaves[i] for i in nd_idx],
                        name=jnp_fn.__name__)

    return wrapped


# --- generated corpus ------------------------------------------------------
_UNARY = """
abs absolute arccos arccosh arcsin arcsinh arctan arctanh bitwise_invert
bitwise_not cbrt ceil conj conjugate cos cosh degrees exp exp2 expm1 fabs
floor invert isfinite isinf isnan isneginf isposinf log log10 log1p log2
logical_not negative positive radians reciprocal rint sign signbit sin sinh
sqrt square tan tanh trunc angle real imag i0 sinc nan_to_num
acos acosh asin asinh atan atanh deg2rad rad2deg
""".split()

_BINARY = """
add arctan2 bitwise_and bitwise_or bitwise_xor copysign divide equal
float_power floor_divide fmax fmin fmod gcd greater greater_equal heaviside
hypot lcm ldexp left_shift less less_equal logaddexp logaddexp2 logical_and
logical_or logical_xor maximum minimum mod multiply not_equal power remainder
right_shift subtract true_divide divmod pow
atan2 bitwise_left_shift bitwise_right_shift nextafter vecdot
""".split()

_REDUCE = """
all any amax amin argmax argmin cumprod cumsum max mean median min nanargmax
nanargmin nancumprod nancumsum nanmax nanmean nanmedian nanmin nanprod nanstd
nansum nanvar prod ptp std sum var count_nonzero average quantile percentile
""".split()

# functions whose arrays may appear in any positional or keyword slot —
# _wrap_jnp tapes them all.
_OTHER = """
reshape ravel transpose swapaxes moveaxis rollaxis squeeze expand_dims
broadcast_to broadcast_arrays flip fliplr flipud rot90 roll
concatenate stack vstack hstack dstack column_stack split array_split hsplit
vsplit dsplit tile repeat pad
take take_along_axis put_along_axis choose compress extract searchsorted
argsort sort lexsort partition argpartition flatnonzero nonzero argwhere where
diag diagflat diagonal trace tril triu tri eye identity vander
dot vdot inner outer matmul tensordot einsum kron cross
clip round around floor_divide
unique union1d intersect1d setdiff1d setxor1d in1d isin
atleast_1d atleast_2d atleast_3d
meshgrid indices unravel_index ravel_multi_index diag_indices
tril_indices triu_indices
histogram histogram2d histogramdd bincount digitize corrcoef cov
convolve correlate interp gradient diff ediff1d trapezoid
polyval polyfit roots
sort_complex real_if_close
isclose allclose array_equal array_equiv
cumulative_sum
flatnonzero packbits unpackbits
apply_along_axis
nanquantile nanpercentile
insert delete append resize trim_zeros
fill_diagonal
select piecewise
permute_dims matrix_transpose unique_all unique_counts unique_inverse
unique_values
""".split()

__all__ = ["ndarray", "array", "zeros", "ones", "empty", "full", "arange",
           "linspace", "logspace", "geomspace", "zeros_like", "ones_like", "full_like",
           "empty_like", "asarray", "ascontiguousarray", "frombuffer",
           "copy", "may_share_memory", "shares_memory", "astype", "abs",
           "shape", "ndim", "size", "result_type", "can_cast", "promote_types",
           "dtype", "finfo", "iinfo", "bool_", "pi", "e", "inf", "nan",
           "newaxis", "euler_gamma",
           "float16", "float32", "float64", "bfloat16", "int8", "int16",
           "int32", "int64", "uint8", "uint16", "uint32", "uint64"]

_g = globals()
for _name in set(_UNARY):
    if hasattr(jnp, _name):
        _g[_name] = _wrap_jnp(getattr(jnp, _name))
        __all__.append(_name)
for _name in set(_BINARY):
    if hasattr(jnp, _name):
        _g[_name] = _wrap_jnp(getattr(jnp, _name))
        __all__.append(_name)
for _name in set(_REDUCE):
    if hasattr(jnp, _name):
        _g[_name] = _wrap_jnp(getattr(jnp, _name))
        __all__.append(_name)
for _name in set(_OTHER):
    if _name in _g:
        continue
    if hasattr(jnp, _name):
        _g[_name] = _wrap_jnp(getattr(jnp, _name))
        __all__.append(_name)


def _seq_wrap(jnp_fn):
    """Wrapper for functions taking a sequence of arrays first (concat etc.)."""

    @functools.wraps(jnp_fn)
    def wrapped(seq, *args, **kwargs):
        kwargs = _fix_kwargs(dict(kwargs))
        seq = list(seq)
        nd_args = [a for a in seq if isinstance(a, NDArray)]
        if not nd_args:
            return NDArray(jnp_fn(seq, *args, **kwargs))

        def fn(*xs):
            it = iter(xs)
            call = [next(it) if isinstance(a, NDArray) else a for a in seq]
            return jnp_fn(call, *args, **kwargs)

        return apply_op(fn, *nd_args, name=jnp_fn.__name__)

    return wrapped


for _name in ("concatenate", "stack", "vstack", "hstack", "dstack",
              "column_stack", "block"):
    if hasattr(jnp, _name):
        _g[_name] = _seq_wrap(getattr(jnp, _name))
        if _name not in __all__:
            __all__.append(_name)


# meshgrid/broadcast_arrays take arrays as *varargs*, which the general
# _wrap_jnp (registered via _OTHER) handles; they must NOT get _seq_wrap,
# which would iterate the first array as if it were the argument list.

def _percentile_family(jnp_fn):
    """percentile/quantile: the reference spells jnp's `method` kwarg
    `interpolation` (numpy<1.22 name) — accept both."""

    base = _wrap_jnp(jnp_fn)

    @functools.wraps(jnp_fn)
    def wrapped(*args, **kwargs):
        if "interpolation" in kwargs:
            if "method" in kwargs:
                raise TypeError(
                    "pass either method= or interpolation=, not both")
            kwargs["method"] = kwargs.pop("interpolation")
        return base(*args, **kwargs)

    return wrapped


for _name in ("percentile", "quantile", "nanpercentile", "nanquantile"):
    if hasattr(jnp, _name):
        _g[_name] = _percentile_family(getattr(jnp, _name))
        if _name not in __all__:
            __all__.append(_name)

def _in1d_ref(ar1, ar2, assume_unique=False, invert=False):
    """numpy-2 dropped in1d; the reference surface keeps it (flat isin,
    reference multiarray `in1d`)."""
    del assume_unique  # correctness identical; jnp.isin has no such arg
    return jnp.isin(jnp.ravel(ar1), ar2, invert=invert)


_in1d_ref.__name__ = "in1d"  # tape/profiler op name, not the helper's
in1d = _wrap_jnp(_in1d_ref)
__all__.append("in1d")


def put_along_axis(arr, indices, values, axis):
    """numpy semantics: mutates `arr` in place. jnp only offers the
    functional form, so compute it and swap the NDArray's handle (the
    framework's in-place convention: new buffer + version bump).
    `values` routes through apply_op like __setitem__'s value does, so
    gradients flow into a scattered NDArray."""
    if not isinstance(arr, NDArray):
        return _np.put_along_axis(arr, indices, values, axis)
    idx = indices._data if isinstance(indices, NDArray) else indices
    if isinstance(values, NDArray):
        out = apply_op(
            lambda a, v: jnp.put_along_axis(a, idx, v, axis,
                                            inplace=False),
            arr, values, name="put_along_axis")
    else:
        out = apply_op(
            lambda a: jnp.put_along_axis(a, idx, values, axis,
                                         inplace=False),
            arr, name="put_along_axis")
    arr._assign_from(out)


def _ldexp_ref(x1, x2):
    """Reference semantics (multiarray.py:9785): x1 * 2**x2 with FLOAT
    exponents allowed — jnp.ldexp rejects non-integer x2. exp2 promotes
    integer inputs to float like numpy's ldexp."""
    return jnp.multiply(x1, jnp.exp2(x2))


_ldexp_ref.__name__ = "ldexp"  # tape/profiler op name, not the helper's
ldexp = _wrap_jnp(_ldexp_ref)

concat = _g.get("concatenate")


def einsum(subscripts, *operands, **kwargs):
    """Einstein summation (reference: np_einsum_op with path optimizer —
    here XLA does the contraction-order optimization)."""
    kwargs = _fix_kwargs(dict(kwargs))
    nd_args = [a for a in operands if isinstance(a, NDArray)]

    def fn(*xs):
        it = iter(xs)
        call = [next(it) if isinstance(a, NDArray) else a for a in operands]
        return jnp.einsum(subscripts, *call, **kwargs)

    if not nd_args:
        return NDArray(jnp.einsum(subscripts, *operands, **kwargs))
    return apply_op(fn, *nd_args, name="einsum")


# --- dtypes (exported like numpy scalars) ---------------------------------
float16 = _np.float16
float32 = _np.float32
float64 = _np.float64
int8 = _np.int8
int16 = _np.int16
int32 = _np.int32
int64 = _np.int64
uint8 = _np.uint8
uint16 = _np.uint16
uint32 = _np.uint32
uint64 = _np.uint64
bool_ = _np.bool_
try:
    import ml_dtypes

    bfloat16 = ml_dtypes.bfloat16
except ImportError:  # pragma: no cover
    bfloat16 = None

dtype = _np.dtype
finfo = jnp.finfo
iinfo = jnp.iinfo
result_type = jnp.result_type
can_cast = jnp.can_cast
promote_types = jnp.promote_types


# --- creation --------------------------------------------------------------

def _device_of(kwargs):
    dev = kwargs.pop("device", None)
    if dev is None:
        dev = kwargs.pop("ctx", None)
    if dev is None:
        return current_device()
    return dev if isinstance(dev, Device) else Device(dev)


def array(object, dtype=None, **kwargs):  # noqa: A002
    return _nd_array(object, dtype=dtype, device=_device_of(kwargs))


def asarray(a, dtype=None, **kwargs):
    if isinstance(a, NDArray) and (dtype is None or a.dtype == normalize_dtype(dtype)):
        return a
    return array(a, dtype=dtype, **kwargs)


ascontiguousarray = asarray


def frombuffer(buffer, dtype=float, **kwargs):
    return array(_np.frombuffer(buffer, dtype=dtype), **kwargs)


def _creation(jnp_fn):
    def fn(shape, dtype=None, order="C", **kwargs):  # noqa: ARG001
        dev = _device_of(kwargs)
        if dtype is None:
            from ..numpy_extension import default_float_dtype

            dtype = default_float_dtype()
        data = jax.device_put(jnp_fn(shape, normalize_dtype(dtype)),
                              dev.jax_device)
        return NDArray(data, dev)

    return fn


zeros = _creation(jnp.zeros)
ones = _creation(jnp.ones)
empty = _creation(jnp.zeros)  # XLA has no uninitialized buffers


def full(shape, fill_value, dtype=None, order="C", **kwargs):  # noqa: ARG001
    dev = _device_of(kwargs)
    if isinstance(fill_value, NDArray):
        fill_value = fill_value._data
    data = jnp.full(shape, fill_value, normalize_dtype(dtype))
    if dtype is None and isinstance(fill_value, (int, float)) \
            and not isinstance(fill_value, bool) \
            and data.dtype in (jnp.float64, jnp.int64):
        # weak python-scalar fill under x64: 32-bit creation default —
        # unless official-numpy defaults were requested; an explicit
        # 64-bit ARRAY fill keeps its dtype (the honored-request contract)
        from ..numpy_extension import is_np_default_dtype

        if not is_np_default_dtype():
            data = data.astype(jnp.float32 if data.dtype == jnp.float64
                               else jnp.int32)
    return NDArray(jax.device_put(data, dev.jax_device), dev)


def zeros_like(a, dtype=None, **kwargs):  # noqa: ARG001
    x = a._data if isinstance(a, NDArray) else a
    return NDArray(jnp.zeros_like(x, dtype=normalize_dtype(dtype)))


def ones_like(a, dtype=None, **kwargs):  # noqa: ARG001
    x = a._data if isinstance(a, NDArray) else a
    return NDArray(jnp.ones_like(x, dtype=normalize_dtype(dtype)))


def full_like(a, fill_value, dtype=None, **kwargs):  # noqa: ARG001
    x = a._data if isinstance(a, NDArray) else a
    return NDArray(jnp.full_like(x, fill_value, dtype=normalize_dtype(dtype)))


def empty_like(a, dtype=None, **kwargs):
    return zeros_like(a, dtype=dtype, **kwargs)


def arange(start, stop=None, step=1, dtype=None, **kwargs):
    """Reference contract (numpy/multiarray.py:6980): default dtype is
    float32 — even for int arguments — unless npx.set_np(dtype=True)
    switched creation defaults to official numpy (then int64/float64)."""
    dev = _device_of(kwargs)
    if dtype is None:
        from ..numpy_extension import is_np_default_dtype

        data = jnp.arange(start, stop, step) if is_np_default_dtype() \
            else jnp.arange(start, stop, step, jnp.float32)
    else:
        data = jnp.arange(start, stop, step, normalize_dtype(dtype))
    return NDArray(jax.device_put(data, dev.jax_device), dev)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, **kwargs):
    dev = _device_of(kwargs)
    out = jnp.linspace(start, stop, num, endpoint=endpoint, retstep=retstep,
                       dtype=normalize_dtype(dtype), axis=axis)
    if retstep:
        return NDArray(jax.device_put(out[0], dev.jax_device), dev), out[1]
    return NDArray(jax.device_put(out, dev.jax_device), dev)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, **kwargs):
    dev = _device_of(kwargs)
    out = jnp.logspace(start, stop, num, endpoint=endpoint, base=base,
                       dtype=normalize_dtype(dtype), axis=axis)
    return NDArray(jax.device_put(out, dev.jax_device), dev)


def geomspace(start, stop, num=50, endpoint=True, dtype=None, axis=0,
              **kwargs):
    dev = _device_of(kwargs)
    out = jnp.geomspace(start, stop, num, endpoint=endpoint,
                        dtype=normalize_dtype(dtype), axis=axis)
    return NDArray(jax.device_put(out, dev.jax_device), dev)


def copy(a):
    return a.copy() if isinstance(a, NDArray) else array(a)


def astype(a, dtype):
    return a.astype(dtype)


def shape(a):
    return a.shape if isinstance(a, NDArray) else _np.shape(a)


def ndim(a):
    return a.ndim if isinstance(a, NDArray) else _np.ndim(a)


def size(a, axis=None):
    if isinstance(a, NDArray):
        return a.size if axis is None else a.shape[axis]
    return _np.size(a, axis)


def may_share_memory(a, b, max_work=None):  # noqa: ARG001
    da = a._data if isinstance(a, NDArray) else a
    db = b._data if isinstance(b, NDArray) else b
    return da is db


shares_memory = may_share_memory


# --- aliases & misc (array-api names, legacy spellings) --------------------

NAN = NaN = nan
NINF = -_np.inf
PINF = _np.inf
NZERO = -0.0
PZERO = 0.0

round_ = _g.get("round")
row_stack = _g.get("vstack")
fix = _g.get("trunc")  # same semantics: round toward zero
__all__ += ["fix"]
_g["bool"] = _np.bool_


def blackman(M, dtype=None, **kwargs):
    return array(_np.blackman(M), dtype=dtype or _np.float32, **kwargs)


def hamming(M, dtype=None, **kwargs):
    return array(_np.hamming(M), dtype=dtype or _np.float32, **kwargs)


def hanning(M, dtype=None, **kwargs):
    return array(_np.hanning(M), dtype=dtype or _np.float32, **kwargs)


def from_dlpack(x):
    return NDArray(jnp.from_dlpack(x))


def genfromtxt(*args, **kwargs):
    return array(_np.genfromtxt(*args, **kwargs))


def set_printoptions(*args, **kwargs):
    _np.set_printoptions(*args, **kwargs)


def diag_indices_from(arr):
    x = arr._data if isinstance(arr, NDArray) else arr
    return tuple(NDArray(i) for i in jnp.diag_indices_from(x))


def tril_indices_from(arr, k=0):
    x = arr._data if isinstance(arr, NDArray) else arr
    return tuple(NDArray(i) for i in jnp.tril_indices_from(x, k))


def triu_indices_from(arr, k=0):
    x = arr._data if isinstance(arr, NDArray) else arr
    return tuple(NDArray(i) for i in jnp.triu_indices_from(x, k))


boolean_dtypes = (_np.bool_,)
integer_dtypes = (_np.int8, _np.int16, _np.int32, _np.int64,
                  _np.uint8, _np.uint16, _np.uint32, _np.uint64)
floating_dtypes = (_np.float16, _np.float32, _np.float64)
numeric_dtypes = integer_dtypes + floating_dtypes

__all__ += ["boolean_dtypes", "integer_dtypes", "floating_dtypes",
            "numeric_dtypes"]
__all__ += ["NAN", "NaN", "NINF", "PINF", "NZERO", "PZERO", "round_",
            "row_stack", "bool", "blackman", "hamming", "hanning",
            "from_dlpack", "genfromtxt", "set_printoptions", "concat",
            "diag_indices_from", "tril_indices_from", "triu_indices_from"]


# --- creation default-dtype policy (reference:
# tests/python/unittest/test_numpy_default_dtype.py) ------------------------
# Float-creation functions answer float32 by default and float64 under
# npx.set_np(dtype=True); x64 being enabled would otherwise leak jnp's
# float64 defaults through the dtype-less spellings.
def _float_default_wrap(fn):
    import functools
    import inspect

    try:
        params = list(inspect.signature(fn).parameters)
        dtype_pos = params.index("dtype") if "dtype" in params else None
    except (TypeError, ValueError):
        dtype_pos = None

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        # only inject when dtype arrived neither as kwarg nor positionally
        # (np.tri(3, 3, 0, 'int32') is legal numpy spelling)
        if "dtype" not in kwargs and (dtype_pos is None
                                      or len(args) <= dtype_pos):
            from ..numpy_extension import default_float_dtype

            kwargs["dtype"] = default_float_dtype()
        return fn(*args, **kwargs)

    return wrapped


for _name in ("eye", "identity", "linspace", "logspace", "geomspace",
              "tri", "hanning", "hamming", "blackman"):
    if _name in _g:
        _g[_name] = _float_default_wrap(_g[_name])
del _name
