"""mx.np.random — numpy-compatible random sampling over jax PRNG.

Reference: src/operator/numpy/random/ (`_npi_*` sampling ops) and
python/mxnet/numpy/random.py. Stateful global key lives in mx._random.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .. import _random
from ..base import normalize_dtype
from ..ndarray.ndarray import NDArray

__all__ = ["seed", "uniform", "normal", "randn", "rand", "randint", "choice",
           "shuffle", "permutation", "gamma", "beta", "exponential", "poisson",
           "bernoulli", "binomial", "negative_binomial", "multinomial", "dirichlet",
           "multivariate_normal", "laplace", "logistic", "gumbel", "pareto",
           "power", "rayleigh", "weibull", "lognormal", "chisquare", "f",
           "standard_normal", "standard_cauchy", "standard_exponential"]

seed = _random.seed


def _shape(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def _f32(dtype):
    d = normalize_dtype(dtype)
    if d is None:
        from ..numpy_extension import default_float_dtype

        return _np.dtype(default_float_dtype())
    return d


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


def uniform(low=0.0, high=1.0, size=None, dtype=None, **kwargs):  # noqa: ARG001
    key = _random.next_key()
    out = jax.random.uniform(key, _shape(size), _f32(dtype),
                             minval=_unwrap(low), maxval=_unwrap(high))
    return NDArray(out)


def normal(loc=0.0, scale=1.0, size=None, dtype=None, **kwargs):  # noqa: ARG001
    key = _random.next_key()
    out = jax.random.normal(key, _shape(size), _f32(dtype))
    return NDArray(out * _unwrap(scale) + _unwrap(loc))


def standard_normal(size=None, dtype=None):
    return normal(0.0, 1.0, size, dtype)


def randn(*shape):
    return normal(size=shape)


def rand(*shape):
    return uniform(size=shape)


def randint(low, high=None, size=None, dtype=None, **kwargs):  # noqa: ARG001
    if high is None:
        low, high = 0, low
    d = normalize_dtype(dtype) or _np.dtype(_np.int32)
    key = _random.next_key()
    out = jax.random.randint(key, _shape(size), int(low), int(high), dtype=d)
    return NDArray(out)


def choice(a, size=None, replace=True, p=None, **kwargs):  # noqa: ARG001
    key = _random.next_key()
    a_ = _unwrap(a)
    if isinstance(a_, int):
        a_ = jnp.arange(a_)
    out = jax.random.choice(key, a_, _shape(size), replace=replace,
                            p=_unwrap(p) if p is not None else None)
    return NDArray(out)


def permutation(x):
    key = _random.next_key()
    x_ = _unwrap(x)
    if isinstance(x_, int):
        x_ = jnp.arange(x_)
    return NDArray(jax.random.permutation(key, x_))


def shuffle(x):
    """In-place shuffle along the first axis (reference: _npi_shuffle)."""
    key = _random.next_key()
    x._data = jax.random.permutation(key, x._data)
    x._version += 1


def gamma(shape, scale=1.0, size=None, dtype=None, **kwargs):  # noqa: ARG001
    key = _random.next_key()
    sz = _shape(size) if size is not None else jnp.shape(_unwrap(shape))
    out = jax.random.gamma(key, _unwrap(shape), sz, _f32(dtype))
    return NDArray(out * _unwrap(scale))


def beta(a, b, size=None, dtype=None):
    key = _random.next_key()
    sz = _shape(size) if size is not None else None
    return NDArray(jax.random.beta(key, _unwrap(a), _unwrap(b), sz, _f32(dtype)))


def exponential(scale=1.0, size=None, dtype=None):
    key = _random.next_key()
    return NDArray(jax.random.exponential(key, _shape(size), _f32(dtype))
                   * _unwrap(scale))


standard_exponential = exponential


def poisson(lam=1.0, size=None, dtype=None):
    key = _random.next_key()
    d = normalize_dtype(dtype) or _np.dtype(_np.int32)
    return NDArray(jax.random.poisson(key, _unwrap(lam), _shape(size), d))


def bernoulli(prob=None, logit=None, size=None, dtype=None):
    key = _random.next_key()
    if prob is None:
        prob = jax.nn.sigmoid(_unwrap(logit))
    else:
        prob = _unwrap(prob)
    sz = _shape(size) if size is not None else jnp.shape(prob)
    out = jax.random.bernoulli(key, prob, sz)
    return NDArray(out.astype(_f32(dtype)))


def binomial(n, p, size=None, dtype=None):
    key = _random.next_key()
    sz = _shape(size) if size is not None else None
    out = jax.random.binomial(key, _unwrap(n), _unwrap(p), shape=sz)
    d = normalize_dtype(dtype)
    return NDArray(out if d is None else out.astype(d))


def negative_binomial(n, p, size=None, dtype=None):  # noqa: ARG001
    # NB(n,p) = Poisson(Gamma(n, (1-p)/p))
    key1 = _random.next_key()
    key2 = _random.next_key()
    n_, p_ = _unwrap(n), _unwrap(p)
    sz = _shape(size)
    lam = jax.random.gamma(key1, n_, sz) * ((1.0 - p_) / p_)
    return NDArray(jax.random.poisson(key2, lam))


def dirichlet(alpha, size=None, dtype=None):
    key = _random.next_key()
    a = jnp.asarray(_unwrap(alpha))
    sz = _shape(size)
    # jax's shape param is the BATCH shape; the event dim is appended
    out = jax.random.dirichlet(key, a, sz if sz else None)
    d = normalize_dtype(dtype)
    return NDArray(out if d is None else out.astype(d))


def multinomial(n, pvals, size=None):
    key = _random.next_key()
    sz = _shape(size)
    out = jax.random.multinomial(key, n, jnp.asarray(_unwrap(pvals)),
                                 shape=sz + jnp.shape(_unwrap(pvals)) if sz else None)
    return NDArray(out)


def multivariate_normal(mean, cov, size=None, check_valid=None, tol=None):  # noqa: ARG001
    key = _random.next_key()
    sz = _shape(size) if size is not None else None
    out = jax.random.multivariate_normal(key, _unwrap(mean), _unwrap(cov),
                                         shape=sz)
    return NDArray(out)


def laplace(loc=0.0, scale=1.0, size=None, dtype=None):
    key = _random.next_key()
    out = jax.random.laplace(key, _shape(size), _f32(dtype))
    return NDArray(out * _unwrap(scale) + _unwrap(loc))


def logistic(loc=0.0, scale=1.0, size=None, dtype=None):
    key = _random.next_key()
    out = jax.random.logistic(key, _shape(size), _f32(dtype))
    return NDArray(out * _unwrap(scale) + _unwrap(loc))


def gumbel(loc=0.0, scale=1.0, size=None, dtype=None):
    key = _random.next_key()
    out = jax.random.gumbel(key, _shape(size), _f32(dtype))
    return NDArray(out * _unwrap(scale) + _unwrap(loc))


def pareto(a, size=None, dtype=None):
    key = _random.next_key()
    return NDArray(jax.random.pareto(key, _unwrap(a), _shape(size), _f32(dtype))
                   - 1.0)


def power(a, size=None, dtype=None):
    key = _random.next_key()
    u = jax.random.uniform(key, _shape(size), _f32(dtype))
    return NDArray(u ** (1.0 / _unwrap(a)))


def rayleigh(scale=1.0, size=None, dtype=None):
    key = _random.next_key()
    u = jax.random.uniform(key, _shape(size), _f32(dtype))
    return NDArray(_unwrap(scale) * jnp.sqrt(-2.0 * jnp.log1p(-u)))


def weibull(a, size=None, dtype=None):
    key = _random.next_key()
    return NDArray(jax.random.weibull_min(key, 1.0, _unwrap(a), _shape(size),
                                          _f32(dtype)))


def lognormal(mean=0.0, sigma=1.0, size=None, dtype=None):
    return normal(mean, sigma, size, dtype).exp()


def chisquare(df, size=None, dtype=None):
    key = _random.next_key()
    return NDArray(jax.random.chisquare(key, _unwrap(df), shape=_shape(size),
                                        dtype=_f32(dtype)))


def f(dfnum, dfden, size=None, dtype=None):
    key = _random.next_key()
    return NDArray(jax.random.f(key, _unwrap(dfnum), _unwrap(dfden),
                                shape=_shape(size), dtype=_f32(dtype)))


def standard_cauchy(size=None, dtype=None):
    key = _random.next_key()
    return NDArray(jax.random.cauchy(key, _shape(size), _f32(dtype)))
