"""Weight initializers (reference: python/mxnet/initializer.py, 832 LoC).

Same registry + string-alias behavior: `net.initialize(init='xavier')` works.
Initializers draw from the global stateful RNG (mx._random) so mx.seed()
reproduces parameter init exactly.
"""
from __future__ import annotations

import json
import math

import jax
import jax.numpy as jnp

from . import _random
from .base import registry
from .ndarray.ndarray import NDArray

_REG = registry("initializer")

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "register", "create"]


def register(klass):
    _REG.register(klass)
    # also register lowercase short alias (Xavier -> xavier)
    return klass


def create(init, **kwargs):
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        if init.startswith("["):  # serialized [name, kwargs] form
            name, kw = json.loads(init)
            return _REG.create(name, **kw)
        return _REG.create(init, **kwargs)
    raise TypeError(f"cannot create initializer from {init!r}")


class Initializer:
    """Base initializer. Subclasses implement _init_weight(name, shape, dtype)
    returning a jax array."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, name, arr=None, explicit=False):
        """Initialize `arr` in place.

        Default initializers dispatch on the parameter name's suffix
        (bias/beta/moving stats → 0, gamma/moving var → 1, else
        _init_weight), mirroring the reference's suffix table. An
        EXPLICITLY chosen initializer (Parameter(init=...) /
        bias_initializer=...) applies its _init_weight regardless of the
        suffix — reference initializer.py:140
        `create(init)._init_weight(desc, arr)` — so e.g.
        LSTMBias/Constant on a bias actually take effect."""
        if arr is None:
            name, arr = getattr(name, "name", str(name)), name
            name = str(name)
        shape, dtype = arr.shape, arr.dtype
        lname = name.lower()
        if explicit:
            data = self._init_weight(name, shape, dtype)
        elif lname.endswith("bias") or lname.endswith("beta") or \
                lname.endswith("running_mean") or lname.endswith("moving_mean"):
            data = jnp.zeros(shape, dtype)
        elif lname.endswith("gamma") or lname.endswith("running_var") or \
                lname.endswith("moving_var"):
            data = jnp.ones(shape, dtype)
        else:
            data = self._init_weight(name, shape, dtype)
        if isinstance(arr, NDArray):
            arr._data = jnp.asarray(data, dtype)
            arr._version += 1
        return arr

    def init_array(self, name, shape, dtype, explicit=False):
        out = NDArray(jnp.zeros(shape, dtype))
        self(name, out, explicit=explicit)
        return out

    def _init_weight(self, name, shape, dtype):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, name, shape, dtype):
        return jnp.zeros(shape, dtype)


_REG.register(Zero, "zeros")


@register
class One(Initializer):
    def _init_weight(self, name, shape, dtype):
        return jnp.ones(shape, dtype)


_REG.register(One, "ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, shape, dtype):
        return jnp.full(shape, self.value, dtype)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, shape, dtype):
        key = _random.next_key()
        return jax.random.uniform(key, shape, jnp.float32, -self.scale,
                                  self.scale).astype(dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, shape, dtype):
        key = _random.next_key()
        return (jax.random.normal(key, shape, jnp.float32)
                * self.sigma).astype(dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, name, shape, dtype):
        key = _random.next_key()
        flat = (shape[0], int(jnp.prod(jnp.asarray(shape[1:]))))
        out = jax.nn.initializers.orthogonal(self.scale)(key, flat, jnp.float32)
        return out.reshape(shape).astype(dtype)


def _fans(shape, factor_type):
    hw = 1
    for d in shape[2:]:
        hw *= d
    fan_out = shape[0] * hw
    fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw
    if factor_type == "avg":
        return (fan_in + fan_out) / 2.0
    if factor_type == "in":
        return fan_in
    return fan_out


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference initializer.py:Xavier; default for Gluon)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = magnitude

    def _init_weight(self, name, shape, dtype):
        factor = max(_fans(shape, self.factor_type), 1.0)
        scale = math.sqrt(self.magnitude / factor)
        key = _random.next_key()
        if self.rnd_type == "uniform":
            w = jax.random.uniform(key, shape, jnp.float32, -scale, scale)
        else:
            w = jax.random.normal(key, shape, jnp.float32) * scale
        return w.astype(dtype)


@register
class MSRAPrelu(Xavier):
    """He initialization (reference: MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference: Bilinear, for Deconvolution)."""

    def _init_weight(self, name, shape, dtype):
        import numpy as onp

        weight = onp.zeros(shape, dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            flat = weight.reshape(-1)
            flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype)


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (reference: LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, shape, dtype):
        b = jnp.zeros(shape, dtype)
        n = shape[0] // 4
        return b.at[n : 2 * n].set(self.forget_bias)


# friendly aliases matching the reference registry
_REG.register(Xavier, "xavier")
_REG.register(MSRAPrelu, "msra")
_REG.register(Normal, "gaussian")
_REG.register(Uniform, "uniform")
_REG.register(Normal, "normal")
_REG.register(Zero, "zero")
_REG.register(One, "one")
