"""Weight initializers (reference: python/mxnet/initializer.py, 832 LoC).

Same registry + string-alias behavior: `net.initialize(init='xavier')` works.
Initializers draw from the global stateful RNG (mx._random) so mx.seed()
reproduces parameter init exactly.
"""
from __future__ import annotations

import json
import math

import jax
import jax.numpy as jnp

from . import _random
from .base import registry
from .ndarray.ndarray import NDArray

_REG = registry("initializer")

__all__ = ["Initializer", "InitDesc", "Zero", "One", "Constant", "Uniform",
           "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "Mixed", "Load", "RNNFused", "register", "create"]


def register(klass):
    _REG.register(klass)
    # also register lowercase short alias (Xavier -> xavier)
    return klass


def create(init, **kwargs):
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        if init.startswith("["):  # serialized [name, kwargs] form
            name, kw = json.loads(init)
            return _REG.create(name, **kw)
        return _REG.create(init, **kwargs)
    raise TypeError(f"cannot create initializer from {init!r}")


class Initializer:
    """Base initializer. Subclasses implement _init_weight(name, shape, dtype)
    returning a jax array."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, name, arr=None, explicit=False):
        """Initialize `arr` in place.

        Mirrors the reference's dispatch protocol (initializer.py:140):
        if `name` is an InitDesc carrying a declared init in
        attrs['__init__'] (the Gluon Parameter path), that declared
        initializer's _init_weight applies regardless of the name
        suffix. `explicit=True` forces THIS initializer's _init_weight
        the same way. Otherwise the legacy suffix table runs
        (bias/beta/moving stats → 0, gamma/moving var → 1, else
        _init_weight). Global initializers with a custom __call__
        (Load, Mixed) never consult the declared init — they drive,
        exactly like the reference."""
        if arr is None:
            name, arr = getattr(name, "name", str(name)), name
            name = str(name)
        declared = None
        attrs = getattr(name, "attrs", None)
        if attrs:
            declared = attrs.get("__init__")
        name = str(name)
        shape, dtype = arr.shape, arr.dtype
        lname = name.lower()
        if declared is not None:
            data = create(declared)._init_weight(name, shape, dtype)
        elif explicit:
            data = self._init_weight(name, shape, dtype)
        elif lname.endswith("bias") or lname.endswith("beta") or \
                lname.endswith("running_mean") or lname.endswith("moving_mean"):
            data = jnp.zeros(shape, dtype)
        elif lname.endswith("gamma") or lname.endswith("running_var") or \
                lname.endswith("moving_var"):
            data = jnp.ones(shape, dtype)
        else:
            data = self._init_weight(name, shape, dtype)
        if isinstance(arr, NDArray):
            arr._data = jnp.asarray(data, dtype)
            arr._version += 1
        return arr

    def init_array(self, name, shape, dtype, explicit=False):
        out = NDArray(jnp.zeros(shape, dtype))
        self(name, out, explicit=explicit)
        return out

    def _init_weight(self, name, shape, dtype):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, name, shape, dtype):
        return jnp.zeros(shape, dtype)


_REG.register(Zero, "zeros")


@register
class One(Initializer):
    def _init_weight(self, name, shape, dtype):
        return jnp.ones(shape, dtype)


_REG.register(One, "ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, shape, dtype):
        return jnp.full(shape, self.value, dtype)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, shape, dtype):
        key = _random.next_key()
        return jax.random.uniform(key, shape, jnp.float32, -self.scale,
                                  self.scale).astype(dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, shape, dtype):
        key = _random.next_key()
        return (jax.random.normal(key, shape, jnp.float32)
                * self.sigma).astype(dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, name, shape, dtype):
        key = _random.next_key()
        flat = (shape[0], int(jnp.prod(jnp.asarray(shape[1:]))))
        out = jax.nn.initializers.orthogonal(self.scale)(key, flat, jnp.float32)
        return out.reshape(shape).astype(dtype)


def _fans(shape, factor_type):
    hw = 1
    for d in shape[2:]:
        hw *= d
    fan_out = shape[0] * hw
    fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw
    if factor_type == "avg":
        return (fan_in + fan_out) / 2.0
    if factor_type == "in":
        return fan_in
    return fan_out


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference initializer.py:Xavier; default for Gluon)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = magnitude

    def _init_weight(self, name, shape, dtype):
        factor = max(_fans(shape, self.factor_type), 1.0)
        scale = math.sqrt(self.magnitude / factor)
        key = _random.next_key()
        if self.rnd_type == "uniform":
            w = jax.random.uniform(key, shape, jnp.float32, -scale, scale)
        else:
            w = jax.random.normal(key, shape, jnp.float32) * scale
        return w.astype(dtype)


@register
class MSRAPrelu(Xavier):
    """He initialization (reference: MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference: Bilinear, for Deconvolution)."""

    def _init_weight(self, name, shape, dtype):
        import numpy as onp

        weight = onp.zeros(shape, dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            flat = weight.reshape(-1)
            flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype)


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (reference: LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, shape, dtype):
        b = jnp.zeros(shape, dtype)
        n = shape[0] // 4
        return b.at[n : 2 * n].set(self.forget_bias)


class InitDesc(str):
    """Parameter-name descriptor carrying init attrs (reference:
    initializer.py InitDesc — a str subclass so it drops into every
    name-taking API)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Mixed(Initializer):
    """Route parameters to initializers by name-regex patterns
    (reference: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        import re

        super().__init__()
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must pair up")
        self.map = [(re.compile(p), create(i)) for p, i in
                    zip(patterns, initializers)]

    def __call__(self, name, arr=None, explicit=False):  # noqa: ARG002
        if arr is None:
            name, arr = getattr(name, "name", str(name)), name
        name = str(name)  # the matched pattern drives, not declared inits
        for prog, init in self.map:
            if prog.match(name):
                return init(name, arr, explicit=True)
        raise ValueError(
            f"Parameter name {name} did not match any pattern; consider "
            "adding a '.*' pattern at the end with a default initializer")


class Load(Initializer):
    """Initialize from a saved name→array dict / .npz path, falling back
    to `default_init` for missing names (reference: initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        if isinstance(param, str):
            from .ndarray.utils import load as _load

            param = _load(param)
        self.param = {}
        for name, arr in dict(param).items():
            key = name[4:] if name.startswith(("arg:", "aux:")) else name
            self.param[key] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr=None, explicit=False):  # noqa: ARG002
        if arr is None:
            name, arr = getattr(name, "name", str(name)), name
        name = str(name)
        if name in self.param:
            src = self.param[name]
            src_np = src.asnumpy() if hasattr(src, "asnumpy") else src
            if tuple(arr.shape) != tuple(src_np.shape):
                raise ValueError(
                    f"Parameter {name} cannot be initialized from "
                    f"loading: shape mismatch, target {tuple(arr.shape)} "
                    f"vs loaded {tuple(src_np.shape)}")
            arr._data = jnp.asarray(src_np, arr.dtype)
            arr._version += 1
            return arr
        if self.default_init is None:
            raise ValueError(
                f"Cannot initialize {name}: not in the loaded params and "
                "no default initializer was provided")
        # the caller chose this fallback — apply it verbatim
        return create(self.default_init)(name, arr, explicit=True)


@register
class RNNFused(Initializer):
    """Initialize a fused-RNN flat parameter blob: weight segments from
    the (optional) per-segment initializers or Uniform(scale), bias
    segments zero (reference: initializer.py RNNFused; layout per
    ops/rnn.py slice_rnn_params / reference rnn-inl.h)."""

    def __init__(self, mode, num_layers, state_size, bidirectional=False,
                 projection_size=None, scale=0.07,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer=None, h2h_bias_initializer=None,
                 h2r_weight_initializer=None):
        super().__init__(mode=mode, num_layers=num_layers,
                         state_size=state_size, bidirectional=bidirectional,
                         projection_size=projection_size, scale=scale,
                         i2h_weight_initializer=i2h_weight_initializer,
                         h2h_weight_initializer=h2h_weight_initializer,
                         i2h_bias_initializer=i2h_bias_initializer,
                         h2h_bias_initializer=h2h_bias_initializer,
                         h2r_weight_initializer=h2r_weight_initializer)
        from .ops.rnn import _GATES

        self.gates = _GATES[mode]
        self.num_layers = num_layers
        self.state_size = state_size
        self.dirs = 2 if bidirectional else 1
        self.projection_size = projection_size
        self.scale = scale
        mk = lambda i, d: create(i) if i is not None else d  # noqa: E731
        default_w = Uniform(scale)
        self._i2h_w = mk(i2h_weight_initializer, default_w)
        self._h2h_w = mk(h2h_weight_initializer, default_w)
        self._i2h_b = mk(i2h_bias_initializer, Zero())
        self._h2h_b = mk(h2h_bias_initializer, Zero())
        self._h2r_w = mk(h2r_weight_initializer, default_w)

    def _input_size(self, total):
        """Invert ops/rnn.py rnn_param_size for the input width."""
        L, D, G, H = (self.num_layers, self.dirs, self.gates,
                      self.state_size)
        P = self.projection_size
        ghd = G * H * D
        if P:
            rest = (L - 1) * (P * D + P + 2) * ghd + P * H * L * D
            return (total - rest) // ghd - P - 2
        rest = (L - 1) * (H * D + H + 2) * ghd
        return (total - rest) // ghd - H - 2

    def _init_weight(self, name, shape, dtype):
        from .ops.rnn import rnn_param_size

        total = int(shape[0])
        in_size = int(self._input_size(total))
        want = rnn_param_size(self.num_layers, in_size, self.state_size,
                              self.dirs == 2, self._kwargs["mode"],
                              self.projection_size)
        if in_size <= 0 or want != total:
            raise ValueError(
                f"RNNFused: flat size {total} inconsistent with "
                f"mode={self._kwargs['mode']} layers={self.num_layers} "
                f"state={self.state_size}")
        L, D, G, H = (self.num_layers, self.dirs, self.gates,
                      self.state_size)
        P = self.projection_size or 0
        R = P or H
        segs = []

        def seg(init, n, sub):
            segs.append(jnp.ravel(jnp.asarray(
                init.init_array(f"{name}_{sub}", (n,), dtype,
                                explicit=True)._data)))

        for layer in range(L):
            in_l = in_size if layer == 0 else R * D
            for _d in range(D):
                seg(self._i2h_w, G * H * in_l, "i2h_weight")
                seg(self._h2h_w, G * H * R, "h2h_weight")
                if P:
                    seg(self._h2r_w, P * H, "h2r_weight")
        for _ in range(L * D):
            seg(self._i2h_b, G * H, "i2h_bias")
            seg(self._h2h_b, G * H, "h2h_bias")
        return jnp.concatenate(segs).astype(dtype)


# friendly aliases matching the reference registry
_REG.register(Xavier, "xavier")
_REG.register(MSRAPrelu, "msra")
_REG.register(Normal, "gaussian")
_REG.register(Uniform, "uniform")
_REG.register(Normal, "normal")
_REG.register(Zero, "zero")
_REG.register(One, "one")
