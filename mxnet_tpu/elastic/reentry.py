"""Elastic Trainer re-entry: rebuild a live trainer for a new topology.

After a rank dies, the supervisor relaunches the job on the surviving
device set; a *process that survives* (or a freshly restored one that
wants to change plans mid-flight) instead calls :func:`reenter` to swap
the trainer onto a new ShardingPlan in place:

  * the plan is swapped and re-applied (params + grads re-placed under
    the new NamedShardings; optimizer state re-placed per the new
    plan's ZeRO ``state_spec_for``, so fsdp state re-extends along the
    new axis);
  * the kvstore is re-pointed at the new plan and its jitted-collective
    cache dropped (bucket signatures change with the mesh);
  * the TrainStep's compiled whole-step program, eligibility verdict,
    and fused buckets are discarded via :meth:`TrainStep.rebuild` — the
    next call re-traces ONCE for the new world and then runs
    zero-retrace again;
  * the learning rate rescales per :func:`rescale_lr`
    (``MXTPU_ELASTIC_LR_RESCALE``: linear | sqrt | off) — the global
    batch shrinks with the data-parallel world, and linear scaling is
    the classic Goyal et al. rule, sqrt its conservative cousin;
  * the :func:`world_generation` counter bumps and lands in the flight
    identity, so opsd ``/identity`` and the fleetctl table show which
    incarnation of the job each rank is running.

A supervisor-relaunched process doesn't call reenter (its Trainer is
built fresh on the new plan); it inherits the generation via
``MXTPU_ELASTIC_GENERATION`` and stamps it at import through
:func:`current_generation`.
"""
from __future__ import annotations

import math
import os

__all__ = ["reenter", "rescale_lr", "rescale_factor",
           "world_generation", "bump_generation", "current_generation"]

# this process's world generation: 0 for a first launch, inherited from
# the supervisor (MXTPU_ELASTIC_GENERATION) for a relaunch, bumped by
# every in-process reenter()
_generation = [None]


def current_generation():
    """The generation this process STARTED at (env-inherited, else 0)."""
    raw = os.environ.get("MXTPU_ELASTIC_GENERATION")
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


def world_generation():
    """The live generation counter: starts at :func:`current_generation`,
    +1 per :func:`reenter` / :func:`bump_generation`."""
    if _generation[0] is None:
        _generation[0] = current_generation()
    return _generation[0]


def bump_generation():
    """Increment the generation and stamp it into the flight identity
    (-> opsd /identity -> fleetctl) and the world_generation gauge."""
    g = world_generation() + 1
    _generation[0] = g
    _stamp(g)
    return g


def _stamp(g):
    from ..telemetry import instruments as _telemetry

    _telemetry.set_world_generation(g)
    try:
        from ..observability import flight as _flight

        _flight.set_identity(generation=g)
    except Exception:
        pass


def rescale_factor(old_world, new_world, mode=None):
    """LR multiplier for a world-size change: 'linear' (new/old, the
    Goyal et al. global-batch rule), 'sqrt' (sqrt(new/old)), 'off'
    (1.0). ``mode=None`` reads MXTPU_ELASTIC_LR_RESCALE."""
    if mode is None:
        from .. import env as _env

        mode = _env.get("MXTPU_ELASTIC_LR_RESCALE")
    mode = str(mode).strip().lower()
    old_world = max(int(old_world), 1)
    new_world = max(int(new_world), 1)
    if mode in ("off", "0", "none", "false", ""):
        return 1.0
    if mode == "linear":
        return new_world / old_world
    if mode == "sqrt":
        return math.sqrt(new_world / old_world)
    raise ValueError(
        f"MXTPU_ELASTIC_LR_RESCALE={mode!r} is not a recognized mode; "
        f"expected linear | sqrt | off")


def rescale_lr(optimizer, old_world, new_world, mode=None):
    """Apply :func:`rescale_factor` to an optimizer's learning rate in
    place; returns the factor. A scheduled LR (lr_scheduler) is left
    alone — schedules already see the new ``rescale_grad``/batch and
    must stay the single source of truth."""
    factor = rescale_factor(old_world, new_world, mode)
    if factor != 1.0 and getattr(optimizer, "lr_scheduler", None) is None:
        optimizer.set_learning_rate(optimizer.learning_rate * factor)
    return factor


def reenter(trainer, plan, train_step=None, lr_rescale=None):
    """Re-enter a live trainer on a new ShardingPlan (docs/elasticity.md).

    ``plan`` is a ShardingPlan, an axes spelling ('dp=2,fsdp=2'), or
    None (drop to replicated). ``train_step`` (optional) is the
    TrainStep to rebuild for the new world. Returns a report dict
    ({'generation', 'old_world', 'new_world', 'lr_factor'}).
    """
    import time

    from ..sharding.plan import ShardingPlan
    from ..telemetry import instruments as _telemetry

    t0 = time.perf_counter()
    old_plan = trainer.sharding_plan
    old_world = old_plan.mesh.devices.size if old_plan is not None else 1
    if plan is not None and not isinstance(plan, ShardingPlan):
        plan = ShardingPlan(plan)
    trainer.set_sharding_plan(plan)
    new_world = plan.mesh.devices.size if plan is not None else 1
    kv = trainer._kvstore
    if kv is not None:
        # mesh-shaped jitted collectives (bucketed allreduce signatures
        # carry the operand shardings) must rebuild for the new world
        cache = getattr(kv, "_sum_cache", None)
        if cache is not None:
            cache.clear()
    if train_step is not None:
        train_step.rebuild()
    factor = rescale_lr(trainer._optimizer, old_world, new_world,
                        lr_rescale)
    g = bump_generation()
    ms = (time.perf_counter() - t0) * 1e3
    _telemetry.record_elastic_restart("reenter", generation=g)
    _telemetry.record_reshard(ms, saved_world=old_world,
                              target_world=new_world, site="reenter")
    return {"generation": g, "old_world": old_world,
            "new_world": new_world, "lr_factor": factor}
