"""Mesh-migrating checkpoint restore (docs/elasticity.md).

Checkpoints are placement-free by construction: ``snapshot.capture``
host-gathers every shard (``asnumpy()`` on a NamedSharding array reads
the full logical value), so the payload of a dp=4 run and an
fsdp=2·tp=2 run of the same model is byte-identical. Resharding is
therefore not an array-rewrite problem — it is a *contract* problem:

  * :func:`plan_compatibility` judges a saved plan manifest against a
    target plan: ``exact`` (same resolved axes), ``replace`` (same
    world size, different placement — restore re-places silently, the
    PR-12 contract) or ``reshard`` (different world size — a topology
    migration that :class:`PlanMismatch` gates behind
    ``allow_reshard=True``);
  * :func:`resharded_restore` is the opt-in front door: it calls
    ``CheckpointManager.restore(..., allow_reshard=True)`` and returns
    the compatibility report alongside the RestoreResult;
  * :func:`reshard_checkpoint` rewrites a committed checkpoint OFFLINE
    for a target mesh: same arrays, the manifest's recorded plan
    replaced by the target plan and the payload re-split across the
    target world's shard files — the output restores onto the new
    topology as an ``exact`` match, with the full tmp+fsync+rename
    commit protocol so a crash mid-rewrite never leaves a half
    checkpoint;
  * :func:`verify_parity` proves a restore bitwise against the
    checkpoint's own host-gathered truth (params AND optimizer state),
    the acceptance oracle tests/test_elastic.py runs on the
    8-virtual-device CPU mesh.

ZeRO re-extension needs no special code here: ``snapshot.apply``
re-places restored optimizer state via ``place_state_like`` under the
RESTORING plan's ``state_spec_for``, so state saved 1/4-per-rank under
fsdp=4 lands 1/2-per-rank under fsdp=2 (or replicated) from the same
logical arrays.
"""
from __future__ import annotations

import json
import os
import shutil
import time

import numpy as np

from ..checkpoint.errors import CheckpointError, PlanMismatch

__all__ = ["PlanMismatch", "plan_compatibility", "plan_world_size",
           "resharded_restore", "reshard_checkpoint", "verify_parity"]


def _as_manifest(plan):
    """A plan argument -> manifest dict (or None): accepts a manifest
    dict already, a ShardingPlan, or an axes spelling."""
    if plan is None or isinstance(plan, dict):
        return plan
    from ..sharding.plan import ShardingPlan

    if not isinstance(plan, ShardingPlan):
        plan = ShardingPlan(plan)
    return plan.to_manifest()


def plan_world_size(plan_manifest):
    """Device count a plan manifest's mesh spans (the product of its
    axis sizes); 1 for None (unsharded = one logical device view).
    -1 (uninferred) sizes resolve against this host's device count."""
    if plan_manifest is None:
        return 1
    total, infer = 1, 0
    for _name, size in plan_manifest.get("axes") or ():
        if int(size) == -1:
            infer += 1
        else:
            total *= int(size)
    if infer:
        import jax

        n = len(jax.devices())
        total = n if total == 0 else max(n // total, 1) ** infer * total
    return total


def plan_compatibility(saved, target):
    """Judge a saved plan against a target plan. Both may be manifests,
    ShardingPlans, axes spellings, or None. Returns a JSON-able report:

      verdict   'exact'    same resolved axes (a plain resume),
                'replace'  same world size, different placement —
                           restore() re-places silently,
                'reshard'  different world size — restore() raises
                           PlanMismatch unless allow_reshard=True;
      saved_world / target_world / saved_axes / target_axes / notes.
    """
    saved = _as_manifest(saved)
    target = _as_manifest(target)
    sw, tw = plan_world_size(saved), plan_world_size(target)
    s_axes = [list(a) for a in (saved or {}).get("axes") or []]
    t_axes = [list(a) for a in (target or {}).get("axes") or []]
    notes = []
    if s_axes == t_axes:
        verdict = "exact"
    elif sw == tw:
        verdict = "replace"
        notes.append("same world size: restore() re-places arrays "
                     "under the target plan silently")
    else:
        verdict = "reshard"
        notes.append(
            f"world size changes {sw} -> {tw}: restore() raises "
            f"PlanMismatch unless allow_reshard=True "
            f"(elastic.resharded_restore / tools/ckpt.py reshard)")
    if (saved or {}).get("zero_axis") != (target or {}).get("zero_axis"):
        notes.append(
            f"ZeRO axis changes "
            f"{(saved or {}).get('zero_axis')!r} -> "
            f"{(target or {}).get('zero_axis')!r}: optimizer state "
            f"re-extends along the target fsdp axis on restore")
    return {"verdict": verdict, "compatible": verdict != "reshard",
            "saved_world": sw, "target_world": tw,
            "saved_axes": s_axes, "target_axes": t_axes, "notes": notes}


def resharded_restore(manager, step=None, trainer=None):
    """Restore a checkpoint onto a trainer whose plan differs from the
    saved one — the explicit opt-in for world-size migrations.

    Thin, auditable front door over ``manager.restore(...,
    allow_reshard=True)``: the manager itself times the re-placement
    (``reshard_ms``) and stamps the flight recorder. Returns
    ``(RestoreResult, compatibility report)``.
    """
    result = manager.restore(step=step, trainer=trainer,
                             allow_reshard=True)
    tr = trainer or manager._trainer
    saved = (result.manifest.get("meta") or {}).get("sharding_plan")
    target = getattr(tr, "sharding_plan", None)
    return result, plan_compatibility(saved, target)


def reshard_checkpoint(src, dst, target_plan=None, *, step=None,
                       target_world=1, mode="replicated", verify=True):
    """Rewrite a committed checkpoint for a target mesh, offline.

    Reads the checkpoint at ``src`` (latest committed step unless
    ``step``), then writes a NEW committed checkpoint under ``dst``
    whose manifest records ``target_plan`` (a ShardingPlan, axes
    spelling, manifest dict, or None for replicated) as the run's plan
    and whose payload is split across ``target_world`` shard files in
    ``mode`` ('replicated': one arrays.npz; 'sharded': round-robin
    shard-NNNNN.npz, the exact split a ``target_world``-rank sharded
    save would produce). Arrays are copied verbatim — the logical state
    is placement-free — so the output restores onto the target topology
    as an ``exact`` plan match. The write runs the same
    tmp+fsync+rename commit protocol as a live save. Returns a report
    dict ({'step', 'dst', 'arrays', 'nbytes', 'compatibility'}).
    """
    from ..checkpoint import manager as _mgr
    from ..telemetry import instruments as _telemetry

    t0 = time.perf_counter()
    src = os.path.abspath(str(src))
    dst = os.path.abspath(str(dst))
    steps = []
    for n in os.listdir(src):
        s = _mgr._step_of(n)
        if s is not None and os.path.isfile(
                os.path.join(src, n, _mgr.MANIFEST_NAME)):
            steps.append(s)
    if step is None:
        if not steps:
            from ..checkpoint.errors import CheckpointNotFound

            raise CheckpointNotFound(f"no committed checkpoint in {src}")
        step = max(steps)
    step = int(step)
    d = os.path.join(src, _mgr._STEP_FMT.format(step))
    arrays, manifest = _mgr._read_checkpoint(d, verify=verify)

    target = _as_manifest(target_plan)
    compat = plan_compatibility(
        (manifest.get("meta") or {}).get("sharding_plan"), target)
    target_world = int(target_world)
    mode = str(mode).lower()
    if mode not in ("replicated", "sharded"):
        raise CheckpointError(
            f"mode must be 'replicated' or 'sharded', got {mode!r}")
    names = sorted(arrays)
    if mode == "sharded" and target_world > 1:
        files = {n: f"shard-{i % target_world:05d}.npz"
                 for i, n in enumerate(names)}
    else:
        files = {n: "arrays.npz" for n in names}

    out = dict(manifest)
    out["world_size"] = target_world
    out["mode"] = mode
    out["reason"] = "reshard"
    out["time"] = time.time()
    out["meta"] = dict(manifest.get("meta") or {})
    out["meta"]["sharding_plan"] = target
    out["arrays"] = {
        n: {"file": files[n], "shape": list(arrays[n].shape),
            "dtype": str(arrays[n].dtype), "crc32": _mgr._crc(arrays[n]),
            "nbytes": int(arrays[n].nbytes)}
        for n in names}

    from .._dtype_codec import encode_payload

    os.makedirs(dst, exist_ok=True)
    final = os.path.join(dst, _mgr._STEP_FMT.format(step))
    tmp = os.path.join(dst, _mgr._TMP_FMT.format(step))
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    for fname in sorted(set(files.values())):
        payload = encode_payload(
            {n: np.asarray(arrays[n]) for n in names
             if files[n] == fname})
        with open(os.path.join(tmp, fname), "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
    _mgr._write_json(os.path.join(tmp, _mgr.MANIFEST_NAME), out)
    _mgr._fsync_dir(tmp)
    if os.path.isdir(final):
        shutil.rmtree(final)
    os.replace(tmp, final)
    _mgr._fsync_dir(dst)
    nbytes = sum(e["nbytes"] for e in out["arrays"].values())
    _telemetry.record_reshard(
        (time.perf_counter() - t0) * 1e3,
        saved_world=compat["saved_world"],
        target_world=compat["target_world"], site="offline")
    return {"step": step, "dst": final, "arrays": len(names),
            "nbytes": nbytes, "compatibility": compat}


def verify_parity(trainer, arrays, atol=0.0):
    """Bitwise-compare a trainer's live params + optimizer state against
    a checkpoint's host-gathered arrays (the ``param/{i}`` / ``opt/...``
    namespace ``snapshot.capture`` writes). Returns the number of arrays
    compared; raises CheckpointError naming the first divergent one.
    ``atol=0.0`` (default) is exact — the fp32 acceptance bar."""
    import jax

    def _cmp(name, live):
        want = np.asarray(arrays[name])
        got = np.asarray(live)
        if got.shape != want.shape or got.dtype != want.dtype:
            raise CheckpointError(
                f"parity: {name} is {got.dtype}{got.shape}, checkpoint "
                f"holds {want.dtype}{want.shape}")
        if atol == 0.0:
            ok = np.array_equal(got, want)
        else:
            ok = np.allclose(got, want, atol=atol, rtol=0.0)
        if not ok:
            delta = float(np.max(np.abs(
                got.astype("float64") - want.astype("float64"))))
            raise CheckpointError(
                f"parity: {name} diverges (max |delta| = {delta:g})")

    compared = 0
    for i, p in enumerate(trainer._params):
        _cmp(f"param/{i}", p.logical_data().asnumpy())
        compared += 1
    for i, st in enumerate(trainer._states):
        if st is None:
            continue
        leaves = jax.tree_util.tree_leaves(
            st, is_leaf=lambda x: hasattr(x, "asnumpy"))
        spec_keys = sorted(k for k in arrays if k == f"opt/{i}"
                           or k.startswith(f"opt/{i}."))
        if len(leaves) != len(spec_keys):
            raise CheckpointError(
                f"parity: param {i} has {len(leaves)} state leaves, "
                f"checkpoint holds {len(spec_keys)}")
        for key, leaf in zip(spec_keys, leaves):
            _cmp(key, leaf.asnumpy())
            compared += 1
    return compared
