"""Elastic training: topology change as a supported event, not a crash
(docs/elasticity.md).

Three pieces close the ROADMAP's last half-built pillar:

  * :mod:`.reshard` — mesh-migrating checkpoint restore: judge a saved
    plan against a target plan, gate world-size changes behind the
    typed :class:`PlanMismatch`, rewrite checkpoints offline for a new
    mesh, and prove restores bitwise against host-gathered truth;
  * :mod:`.reentry` — swap a live Trainer onto a new plan: re-place
    params/state, rebuild the donated whole-step program and kvstore
    collectives for the new world, rescale the LR
    (MXTPU_ELASTIC_LR_RESCALE), bump the :func:`world_generation`
    counter into the flight identity;
  * :mod:`.policy` — the supervisor's restart brain (backoff, restart
    budget, clean-exit contract) plus the append-only restart ledger
    tools/supervisor.py writes into the flight dir.
"""
from __future__ import annotations

from .policy import LEDGER_NAME, RestartLedger, RestartPolicy
from .reentry import (bump_generation, current_generation, reenter,
                      rescale_factor, rescale_lr, world_generation)
from .reshard import (PlanMismatch, plan_compatibility, plan_world_size,
                      reshard_checkpoint, resharded_restore, verify_parity)

__all__ = [
    "PlanMismatch", "plan_compatibility", "plan_world_size",
    "resharded_restore", "reshard_checkpoint", "verify_parity",
    "reenter", "rescale_lr", "rescale_factor",
    "world_generation", "bump_generation", "current_generation",
    "RestartPolicy", "RestartLedger", "LEDGER_NAME",
]
