"""Supervisor restart policy + restart ledger (docs/elasticity.md).

Pure decision logic, importable without jax: tools/supervisor.py feeds
it exit observations and it answers restart / give_up with a backoff —
so the policy is unit-testable without launching a single process.

  * Clean exits (the MXTPU_CKPT_PREEMPT_EXIT_CODE contract — the
    PreemptionHandler's snapshot-then-exit path — plus plain 0) mean
    the job FINISHED or was preempted resumably: the supervisor stops.
  * Any other exit is a rank death: restart from the latest good
    checkpoint onto the surviving device set, with exponential backoff
    (MXTPU_ELASTIC_BACKOFF_S doubling up to MXTPU_ELASTIC_BACKOFF_MAX_S)
    and a lifetime budget (MXTPU_ELASTIC_MAX_RESTARTS).

Every decision lands in a :class:`RestartLedger` — an append-only JSON
file in the flight dir, the postmortem record of which incarnations
ran, why each died, and what the supervisor decided.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["RestartPolicy", "RestartLedger", "LEDGER_NAME"]

LEDGER_NAME = "restart_ledger.json"


def _env_get(name, default):
    try:
        from .. import env as _env

        if name in _env.all_vars():
            return _env.get(name)
    except Exception:
        pass
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return type(default)(raw)
    except (TypeError, ValueError):
        return default


class RestartPolicy:
    """Decides what the supervisor does after an incarnation exits."""

    def __init__(self, max_restarts=None, backoff_s=None,
                 backoff_max_s=None, clean_exit_codes=None):
        self.max_restarts = _env_get("MXTPU_ELASTIC_MAX_RESTARTS", 3) \
            if max_restarts is None else int(max_restarts)
        self.backoff_s = _env_get("MXTPU_ELASTIC_BACKOFF_S", 1.0) \
            if backoff_s is None else float(backoff_s)
        self.backoff_max_s = _env_get("MXTPU_ELASTIC_BACKOFF_MAX_S", 30.0) \
            if backoff_max_s is None else float(backoff_max_s)
        if clean_exit_codes is None:
            preempt = _env_get("MXTPU_CKPT_PREEMPT_EXIT_CODE", 0)
            clean_exit_codes = {0, int(preempt)}
        self.clean_exit_codes = frozenset(int(c) for c in clean_exit_codes)
        self.restarts = 0

    def is_clean(self, exit_code):
        """True for the resumable-shutdown contract: 0 or the
        PreemptionHandler's MXTPU_CKPT_PREEMPT_EXIT_CODE."""
        return exit_code in self.clean_exit_codes

    def backoff(self, restart_index=None):
        """Delay before restart N (0-based): base * 2^N, capped."""
        n = self.restarts if restart_index is None else int(restart_index)
        return min(self.backoff_s * (2 ** n), self.backoff_max_s)

    def decide(self, exit_codes):
        """One incarnation ended with per-rank ``exit_codes`` (a dict
        {rank: code} or a list; None entries = killed by the supervisor
        during teardown, not counted as deaths). Returns a decision dict
        {'action': 'stop'|'restart'|'give_up', 'reason', 'backoff_s',
        'dead_ranks'} and (on restart) advances the restart counter.
        """
        if isinstance(exit_codes, dict):
            codes = exit_codes
        else:
            codes = dict(enumerate(exit_codes))
        dead = sorted(r for r, c in codes.items()
                      if c is not None and not self.is_clean(c))
        if not dead:
            return {"action": "stop", "reason": "clean_exit",
                    "backoff_s": 0.0, "dead_ranks": []}
        if self.max_restarts >= 0 and self.restarts >= self.max_restarts:
            return {"action": "give_up",
                    "reason": f"restart budget exhausted "
                              f"({self.max_restarts})",
                    "backoff_s": 0.0, "dead_ranks": dead}
        delay = self.backoff()
        self.restarts += 1
        return {"action": "restart", "reason": "rank_death",
                "backoff_s": delay, "dead_ranks": dead}


class RestartLedger:
    """Append-only restart history in the flight dir.

    One JSON document {'entries': [...]} rewritten atomically
    (tmp+replace) per append — a supervisor crash never truncates it,
    and fleet tooling can read it mid-run.
    """

    def __init__(self, directory):
        self.path = os.path.join(os.path.abspath(str(directory)),
                                 LEDGER_NAME)

    def entries(self):
        try:
            with open(self.path, encoding="utf-8") as f:
                return list(json.load(f).get("entries") or [])
        except (FileNotFoundError, json.JSONDecodeError, OSError):
            return []

    def append(self, **entry):
        entry.setdefault("time", time.time())
        entries = self.entries()
        entries.append(entry)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"entries": entries}, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        return entry
