"""mx.npx.random — the numpy_extension random namespace.

Reference: python/mxnet/numpy_extension/random.py:25
(__all__ = seed, bernoulli, normal_n, uniform_n). The implementations
live in the npx top level; this module is the reference-spelled
namespace so `mx.npx.random.bernoulli(...)` scripts port verbatim.
"""
from __future__ import annotations

from . import bernoulli, normal_n, uniform_n
from .._random import seed  # top-level mx.seed wraps this same entry

__all__ = ["seed", "bernoulli", "normal_n", "uniform_n"]
