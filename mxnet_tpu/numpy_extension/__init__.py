"""mx.npx — operators beyond the NumPy standard (NN primitives etc.).

Reference: python/mxnet/numpy_extension/ (the `_npx_*` namespace: activation,
batch_norm, convolution, pooling, fully_connected, embedding, topk, pick,
one_hot, sequence ops...). Here each wraps a pure op from mxnet_tpu.ops.nn via
apply_op, so they are taped and traceable.
"""
from __future__ import annotations

import functools

from .. import _random
from ..autograd import is_training
from ..ndarray.ndarray import NDArray, apply_op
from ..ops import nn as _nn

from .control_flow import cond, foreach, while_loop  # noqa: F401

__all__ = [
    "cond", "foreach", "while_loop",
    "activation", "leaky_relu", "relu", "sigmoid", "softmax", "log_softmax",
    "softmin", "fully_connected", "convolution", "deconvolution", "pooling",
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "rms_norm",
    "lrn", "dropout", "embedding", "one_hot", "pick", "topk", "sequence_mask",
    "sequence_last", "sequence_reverse", "l2_normalization", "upsampling",
    "moments", "gamma", "erf", "erfinv", "set_np", "reset_np", "is_np_array",
    "is_np_shape", "use_np", "cpu", "gpu", "tpu", "num_gpus", "current_device",
    "waitall",
]


def _op(fn, n_arrays):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        arrs = args[:n_arrays]
        rest = args[n_arrays:]
        nd = [a for a in arrs if isinstance(a, NDArray)]

        def pure(*xs):
            it = iter(xs)
            call = [next(it) if isinstance(a, NDArray) else a for a in arrs]
            return fn(*call, *rest, **kwargs)

        return apply_op(pure, *nd, name=fn.__name__)

    return wrapped


activation = _op(_nn.activation, 1)
leaky_relu = _op(_nn.leaky_relu, 2)
softmax = _op(_nn.softmax, 1)
log_softmax = _op(_nn.log_softmax, 1)
softmin = _op(_nn.softmin, 1)
fully_connected = _op(_nn.dense, 3)
convolution = _op(_nn.conv, 3)
deconvolution = _op(_nn.conv_transpose, 3)
pooling = _op(_nn.pool, 1)
layer_norm = _op(_nn.layer_norm, 3)
group_norm = _op(_nn.group_norm, 3)
instance_norm = _op(_nn.instance_norm, 3)
rms_norm = _op(_nn.rms_norm, 2)
lrn = _op(_nn.lrn, 1)
embedding = _op(_nn.embedding, 2)
one_hot = _op(_nn.one_hot, 1)
pick = _op(_nn.pick, 2)
topk = _op(_nn.topk, 1)
sequence_mask = _op(_nn.sequence_mask, 2)
sequence_last = _op(_nn.sequence_last, 2)
sequence_reverse = _op(_nn.sequence_reverse, 2)
l2_normalization = _op(_nn.l2_normalization, 1)
upsampling = _op(_nn.upsample, 1)
moments = _op(_nn.moments, 1)


def relu(x):
    return activation(x, "relu")


def sigmoid(x):
    return activation(x, "sigmoid")


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               output_mean_var=False, axis=1):
    """Eager batch_norm; updates running stats in place like the reference op
    (mutable aux inputs of nn/batch_norm.cc)."""
    training = is_training() and not use_global_stats
    if fix_gamma:
        gamma = gamma.ones_like()
    out, nm, nv = _op(_nn.batch_norm, 5)(
        x, gamma, beta, running_mean, running_var, eps=eps, momentum=momentum,
        training=training, use_global_stats=use_global_stats, axis=axis)
    if training:
        running_mean._assign_from(nm.detach())
        running_var._assign_from(nv.detach())
    if output_mean_var:
        return out, nm, nv
    return out


def dropout(x, p=0.5, axes=None, mode="training"):
    training = is_training() or mode == "always"
    if not training or p <= 0:
        return x
    key = _random.next_key()
    return _op(_nn.dropout, 1)(x, key, p=p, training=True, axes=axes)


def gamma(x):
    import jax.scipy.special as jsp

    return apply_op(lambda v: jsp.gamma(v) if hasattr(jsp, "gamma")
                    else __import__("jax.numpy", fromlist=["exp"]).exp(jsp.gammaln(v)), x)


def erf(x):
    import jax.scipy.special as jsp

    return apply_op(jsp.erf, x)


def erfinv(x):
    import jax.scipy.special as jsp

    return apply_op(jsp.erfinv, x)


# --- npx namespace/device utilities (API parity) ---------------------------
from ..device import cpu, current_device, gpu, num_gpus, tpu  # noqa: E402
from ..engine import waitall  # noqa: E402

_np_active = True


def set_np(shape=True, array=True, dtype=False):  # noqa: ARG001
    """Parity no-op: this framework is numpy-semantics native."""
    global _np_active
    _np_active = True


def reset_np():
    set_np()


def is_np_array():
    return _np_active


def is_np_shape():
    return _np_active


def use_np(func):
    """Decorator parity with npx.use_np — identity here."""
    return func
