"""mx.npx — operators beyond the NumPy standard (NN primitives etc.).

Reference: python/mxnet/numpy_extension/ (the `_npx_*` namespace: activation,
batch_norm, convolution, pooling, fully_connected, embedding, topk, pick,
one_hot, sequence ops...). Here each wraps a pure op from mxnet_tpu.ops.nn via
apply_op, so they are taped and traceable.
"""
from __future__ import annotations

import functools

from .. import _random
from ..autograd import is_training
from ..ndarray.ndarray import NDArray, apply_op
from ..ops import nn as _nn

from .control_flow import cond, foreach, while_loop  # noqa: F401
from . import image  # noqa: F401  (mx.npx.image — reference:
#                      numpy_extension/image.py op-family namespace)

__all__ = [
    "cond", "foreach", "while_loop",
    "activation", "leaky_relu", "relu", "sigmoid", "softmax", "log_softmax",
    "softmin", "fully_connected", "convolution", "deconvolution", "pooling",
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "rms_norm",
    "lrn", "dropout", "embedding", "one_hot", "pick", "topk", "sequence_mask",
    "sequence_last", "sequence_reverse", "l2_normalization", "upsampling",
    "moments", "gamma", "erf", "erfinv", "set_np", "reset_np", "is_np_array",
    "is_np_shape", "is_np_default_dtype", "use_np", "cpu", "gpu", "tpu",
    "num_gpus", "current_device", "waitall",
]


def _op(fn, n_arrays):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        arrs = args[:n_arrays]
        rest = args[n_arrays:]
        nd = [a for a in arrs if isinstance(a, NDArray)]

        def pure(*xs):
            it = iter(xs)
            call = [next(it) if isinstance(a, NDArray) else a for a in arrs]
            return fn(*call, *rest, **kwargs)

        return apply_op(pure, *nd, name=fn.__name__)

    return wrapped


activation = _op(_nn.activation, 1)
leaky_relu = _op(_nn.leaky_relu, 2)
softmax = _op(_nn.softmax, 1)
log_softmax = _op(_nn.log_softmax, 1)
softmin = _op(_nn.softmin, 1)
fully_connected = _op(_nn.dense, 3)
convolution = _op(_nn.conv, 3)
deconvolution = _op(_nn.conv_transpose, 3)
pooling = _op(_nn.pool, 1)
layer_norm = _op(_nn.layer_norm, 3)
group_norm = _op(_nn.group_norm, 3)
instance_norm = _op(_nn.instance_norm, 3)
rms_norm = _op(_nn.rms_norm, 2)
lrn = _op(_nn.lrn, 1)
embedding = _op(_nn.embedding, 2)
one_hot = _op(_nn.one_hot, 1)
pick = _op(_nn.pick, 2)
topk = _op(_nn.topk, 1)
sequence_mask = _op(_nn.sequence_mask, 2)
sequence_last = _op(_nn.sequence_last, 2)
sequence_reverse = _op(_nn.sequence_reverse, 2)
l2_normalization = _op(_nn.l2_normalization, 1)
upsampling = _op(_nn.upsample, 1)
moments = _op(_nn.moments, 1)


def relu(x):
    return activation(x, "relu")


def sigmoid(x):
    return activation(x, "sigmoid")


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               output_mean_var=False, axis=1):
    """Eager batch_norm; updates running stats in place like the reference op
    (mutable aux inputs of nn/batch_norm.cc)."""
    training = is_training() and not use_global_stats
    if fix_gamma:
        gamma = gamma.ones_like()
    out, nm, nv = _op(_nn.batch_norm, 5)(
        x, gamma, beta, running_mean, running_var, eps=eps, momentum=momentum,
        training=training, use_global_stats=use_global_stats, axis=axis)
    if training:
        running_mean._assign_from(nm.detach())
        running_var._assign_from(nv.detach())
    if output_mean_var:
        return out, nm, nv
    return out


def dropout(x, p=0.5, axes=None, mode="training"):
    training = is_training() or mode == "always"
    if not training or p <= 0:
        return x
    key = _random.next_key()
    return _op(_nn.dropout, 1)(x, key, p=p, training=True, axes=axes)


def gamma(x):
    import jax.scipy.special as jsp

    return apply_op(lambda v: jsp.gamma(v) if hasattr(jsp, "gamma")
                    else __import__("jax.numpy", fromlist=["exp"]).exp(jsp.gammaln(v)), x)


def erf(x):
    import jax.scipy.special as jsp

    return apply_op(jsp.erf, x)


def erfinv(x):
    import jax.scipy.special as jsp

    return apply_op(jsp.erfinv, x)


# --- npx namespace/device utilities (API parity) ---------------------------
from ..device import cpu, current_device, gpu, num_gpus, tpu  # noqa: E402
from ..engine import waitall  # noqa: E402

import threading as _threading

# np-semantics state: process-wide defaults set by set_np, with
# THREAD-LOCAL overrides from the util.np_shape/np_array scopes (the
# reference's MXNET_NPX bits are per-thread; a DataLoader worker must
# not see another thread's scope)
_np_defaults = {"array": True, "shape": True}
_np_tls = _threading.local()
_np_default_dtype = False


def _np_flag(key):
    over = getattr(_np_tls, key, None)
    return _np_defaults[key] if over is None else over


def set_np(shape=True, array=True, dtype=False):
    """Set the process-wide np-semantics defaults (reference:
    util.py set_np — array semantics require shape semantics); `dtype`
    switches creation defaults to official-numpy (float64/int64)
    (numpy/multiarray.py:7004)."""
    global _np_default_dtype
    if array and not shape:
        raise ValueError("set_np: array semantics require shape "
                         "semantics (reference util.py set_np contract)")
    _np_defaults["array"] = bool(array)
    _np_defaults["shape"] = bool(shape)
    _np_default_dtype = bool(dtype)


def reset_np():
    """``set_np(shape=False, array=False, dtype=False)`` — turn every
    np-semantics flag OFF, exactly like the reference's ``reset_np()``
    (util.py).

    On this framework the ``array``/``shape`` flags are ADVISORY: every
    frontend array IS an mx.np array and zero-dim/zero-size shapes are
    always representable, so flipping them does not switch the
    underlying array implementation — it only changes what
    :func:`is_np_array` / :func:`is_np_shape` report to ported code
    paths (and the scope managers util.np_shape/np_array still override
    them thread-locally). The ``dtype`` flag is real either way: after
    ``reset_np()`` creation defaults are float32/int32 again. Code that
    wants the flags back on calls ``set_np()``; see docs/migration.md.
    """
    global _np_default_dtype
    _np_defaults["array"] = False
    _np_defaults["shape"] = False
    _np_default_dtype = False


def is_np_array():
    return _np_flag("array")


def is_np_shape():
    return _np_flag("shape")


def is_np_default_dtype():
    """True when creation defaults follow official numpy (float64/int64);
    False (default) keeps the reference's float32/int32 defaults."""
    return _np_default_dtype


def default_float_dtype():
    """THE creation-default float dtype (one definition — every creation
    path consults this): float64 under npx.set_np(dtype=True), float32
    otherwise."""
    import numpy as _np

    return _np.float64 if _np_default_dtype else _np.float32


def default_int_dtype():
    import numpy as _np

    return _np.int64 if _np_default_dtype else _np.int32


def use_np(func):
    """Decorator parity with npx.use_np — identity here."""
    return func


# --- npx op extras (reference _npx_* ops beyond the NN nucleus) ------------
import jax as _jax  # noqa: E402
import jax.numpy as _jnp  # noqa: E402
import numpy as _onp  # noqa: E402

from ..ndarray.utils import load, save, savez  # noqa: F401,E402

__all__ += [
    "arange_like", "batch_dot", "bernoulli", "broadcast_like", "from_dlpack",
    "from_numpy", "load", "save", "savez", "masked_softmax",
    "masked_log_softmax", "normal_n", "uniform_n", "rnn", "seed",
    "to_dlpack_for_read", "to_dlpack_for_write", "gelu",
]


def seed(s, ctx="all"):
    from .. import seed as _seed

    _seed(s, ctx)


def from_numpy(ndarray_, zero_copy=True):  # noqa: ARG001
    return NDArray(_jnp.asarray(_onp.asarray(ndarray_)))


def from_dlpack(x):
    return NDArray(_jnp.from_dlpack(x))


def to_dlpack_for_read(x):
    """Return the underlying array as a DLPack-protocol object (modern
    DLPack exchange passes the OBJECT, whose __dlpack__ the consumer
    calls — jnp/np.from_dlpack no longer accept bare capsules)."""
    return x._data


to_dlpack_for_write = to_dlpack_for_read


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    """Reference: contrib arange_like — arange shaped like `data`."""

    def pure(x):
        if axis is None:
            n = x.size
            out = start + step * (_jnp.arange(n, dtype=x.dtype) // repeat
                                  if repeat != 1 else _jnp.arange(n, dtype=x.dtype))
            return out.reshape(x.shape)
        n = x.shape[axis]
        idx = _jnp.arange(n, dtype=x.dtype)
        if repeat != 1:
            idx = idx // repeat
        return start + step * idx

    return apply_op(pure, data, name="arange_like")


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    """Batched matmul over leading batch dim (reference: batch_dot op)."""

    def pure(x, y):
        if transpose_a:
            x = _jnp.swapaxes(x, -1, -2)
        if transpose_b:
            y = _jnp.swapaxes(y, -1, -2)
        return _jnp.matmul(x, y)

    return apply_op(pure, a, b, name="batch_dot")


def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    def pure(x, y):
        if lhs_axes is None:
            return _jnp.broadcast_to(x, y.shape)
        shape = list(x.shape)
        for la, ra in zip(lhs_axes, rhs_axes):
            shape[la] = y.shape[ra]
        return _jnp.broadcast_to(x, tuple(shape))

    return apply_op(pure, lhs, rhs, name="broadcast_like")


def masked_softmax(data, mask, axis=-1, temperature=1.0):
    def pure(x, m):
        neg = _jnp.finfo(x.dtype).min
        logits = _jnp.where(m.astype(bool), x / temperature, neg)
        out = _jax.nn.softmax(logits, axis=axis)
        return _jnp.where(m.astype(bool), out, 0.0).astype(x.dtype)

    return apply_op(pure, data, mask, name="masked_softmax")


def masked_log_softmax(data, mask, axis=-1, temperature=1.0):
    def pure(x, m):
        neg = _jnp.finfo(x.dtype).min
        logits = _jnp.where(m.astype(bool), x / temperature, neg)
        out = _jax.nn.log_softmax(logits, axis=axis)
        return _jnp.where(m.astype(bool), out, neg).astype(x.dtype)

    return apply_op(pure, data, mask, name="masked_log_softmax")


def gelu(x, approximate=True):
    return apply_op(lambda v: _jax.nn.gelu(v, approximate=approximate), x,
                    name="gelu")


def bernoulli(prob=None, logit=None, size=None, dtype=None):
    if (prob is None) == (logit is None):
        raise ValueError("pass exactly one of prob/logit")
    key = _random.next_key()
    p = prob if prob is not None else None

    def pure(v):
        pv = v if p is not None else _jax.nn.sigmoid(v)
        shape = size if size is not None else pv.shape
        draw = _jax.random.bernoulli(key, pv, shape=shape)
        return draw.astype(dtype or "float32")

    x = p if p is not None else logit
    if isinstance(x, NDArray):
        return apply_op(pure, x, name="bernoulli")
    return NDArray(pure(_jnp.asarray(x)))


def _sample_n(dist):
    def fn(*params, shape=None, dtype="float32"):
        key = _random.next_key()

        def pure(*xs):
            it = iter(xs)
            ps = [next(it) if isinstance(p, NDArray) else _jnp.asarray(p)
                  for p in params]
            base = _jnp.broadcast_arrays(*ps)[0].shape
            full = tuple(shape or ()) + base
            if dist == "normal":
                loc, scale = ps
                return (loc + scale * _jax.random.normal(key, full)).astype(dtype)
            low, high = ps
            return _jax.random.uniform(
                key, full, minval=low, maxval=high).astype(dtype)

        nd = [p for p in params if isinstance(p, NDArray)]
        if nd:
            return apply_op(pure, *nd, name=f"{dist}_n")
        return NDArray(pure())

    return fn


def normal_n(loc=0.0, scale=1.0, shape=None, dtype="float32"):
    return _sample_n("normal")(loc, scale, shape=shape, dtype=dtype)


def uniform_n(low=0.0, high=1.0, shape=None, dtype="float32"):
    return _sample_n("uniform")(low, high, shape=shape, dtype=dtype)


def rnn(data=None, parameters=None, state=None, state_cell=None, mode="lstm",
        state_size=None, num_layers=1, bidirectional=False, p=0.0,
        state_outputs=False, **kwargs):  # noqa: ARG001
    """Fused multi-layer RNN on a packed parameter vector.

    Reference: src/operator/rnn.cc / rnn-inl.h — one flat `parameters` vector
    holding (all i2h/h2h weights, layer-major, direction-minor) then (all
    biases, same order). TPU re-design: the time loop is a lax.scan per
    layer/direction; the per-step gemms batch onto the MXU.
    data: (T, N, I); state: (L*D, N, H); returns out (T, N, H*D)
    (+ state outputs when state_outputs=True).
    """
    from ..gluon.rnn.rnn_layer import _rnn_step

    H = int(state_size)
    D = 2 if bidirectional else 1
    G = {"lstm": 4, "gru": 3}.get(mode, 1)
    step = _rnn_step(mode if mode != "rnn" else "rnn_tanh")
    has_cell = mode == "lstm"
    train_drop = p > 0 and is_training()
    drop_key = _random.next_key() if train_drop else None

    def pure(x, w, h0, *maybe_c):
        c0 = maybe_c[0] if maybe_c else None
        T, N, in_size = x.shape
        # slice the packed vector: weights (layer-major), then biases
        off = 0
        wi_l, wh_l, bi_l, bh_l = [], [], [], []
        for layer in range(num_layers):
            isz = in_size if layer == 0 else H * D
            for _ in range(D):
                wi_l.append(w[off:off + G * H * isz].reshape(G * H, isz))
                off += G * H * isz
                wh_l.append(w[off:off + G * H * H].reshape(G * H, H))
                off += G * H * H
        for _ in range(num_layers * D):
            bi_l.append(w[off:off + G * H])
            off += G * H
            bh_l.append(w[off:off + G * H])
            off += G * H

        def run_dir(seq, idx, reverse):
            hc = (h0[idx],) if not has_cell else (h0[idx], c0[idx])
            wi, wh, bi, bh = wi_l[idx], wh_l[idx], bi_l[idx], bh_l[idx]
            xs = seq[::-1] if reverse else seq
            carry, ys = _jax.lax.scan(
                lambda c, xt: step(c, xt, wi, wh, bi, bh), hc, xs)
            return carry, (ys[::-1] if reverse else ys)

        seq = x
        h_fin, c_fin = [], []
        for layer in range(num_layers):
            outs = []
            for d in range(D):
                idx = layer * D + d
                carry, ys = run_dir(seq, idx, reverse=(d == 1))
                outs.append(ys)
                h_fin.append(carry[0])
                if has_cell:
                    c_fin.append(carry[1])
            seq = outs[0] if D == 1 else _jnp.concatenate(outs, axis=-1)
            if train_drop and layer < num_layers - 1:
                keep = 1.0 - p
                mask = _jax.random.bernoulli(
                    _jax.random.fold_in(drop_key, layer), keep, seq.shape)
                seq = _jnp.where(mask, seq / keep, 0.0).astype(seq.dtype)
        outs = [seq, _jnp.stack(h_fin)]
        if has_cell:
            outs.append(_jnp.stack(c_fin))
        return tuple(outs)

    args = [data, parameters, state] + ([state_cell] if has_cell else [])
    res = apply_op(pure, *args, name="rnn")
    if state_outputs:
        return res
    return res[0]


# ---------------------------------------------------------------------------
# generated corpus: expose every registry op under npx as well (reference
# npx carries the full `_npx_*` surface — topk/pick/gather_nd/reshape_like/
# the linalg family/legacy vision ops...). Hand-written wrappers above win,
# so define the stateful CamelCase spellings BEFORE populate (the registry's
# pure `Dropout`/`BatchNorm` would otherwise be silent no-op traps).
# ---------------------------------------------------------------------------


def Dropout(data, p=0.5, mode="training", axes=None, **kwargs):  # noqa: ARG001, N802
    return dropout(data, p=p, axes=axes, mode=mode)


def BatchNorm(data, gamma, beta, moving_mean, moving_var, **kwargs):  # noqa: N802
    return batch_norm(data, gamma, beta, moving_mean, moving_var, **kwargs)


def npx_reshape_shape(src, target):
    """Resolve the _npx_reshape code table (reference:
    src/operator/numpy/np_matrix_op.cc NumpyXReshapeInferShape): -1 infer,
    -2 copy-dim, -3 skip size-1 dim, -4 copy-all-remaining, -5 merge-two,
    -6 split (next two entries, either may be -1)."""
    src = list(src)
    target = list(target)
    if all(t >= 0 for t in target):
        return tuple(target)
    out = []
    i = 0  # src index
    j = 0
    infer_at = -1
    known = 1
    while j < len(target):
        t = target[j]
        if t == -1:
            infer_at = len(out)
            out.append(-1)
            i += 1
        elif t == -2:
            out.append(src[i])
            known *= src[i]
            i += 1
        elif t == -3:
            if src[i] != 1:
                raise ValueError("-3 may only skip a size-1 dim")
            i += 1
        elif t == -4:
            while i < len(src):
                out.append(src[i])
                known *= src[i]
                i += 1
        elif t == -5:
            merged = src[i] * src[i + 1]
            out.append(merged)
            known *= merged
            i += 2
        elif t == -6:
            # operands are read from the (possibly reversed) target, exactly
            # like the reference's NumpyXReshapeInferShape(rev_newshape)
            if j + 2 >= len(target):
                raise ValueError(
                    "-6 needs two following entries in the (possibly "
                    f"reversed) target shape, got {target[j:]}")
            d0 = src[i]
            d1, d2 = target[j + 1], target[j + 2]
            if d1 == -1:
                d1 = d0 // d2
            elif d2 == -1:
                d2 = d0 // d1
            if d1 * d2 != d0:
                raise ValueError(
                    f"split dims ({d1}, {d2}) do not divide source dim {d0}")
            out.extend([d1, d2])
            known *= d1 * d2
            i += 1
            j += 2
        else:
            out.append(t)
            known *= t
            i += 1
        j += 1
    if infer_at >= 0:
        total = 1
        for d in src:
            total *= d
        out[infer_at] = total // known
    return tuple(out)


def reshape(a, newshape, reverse=False, order="C"):  # noqa: ARG001
    """npx.reshape with the _npx_* code table (NOT the legacy nd.reshape
    codes — those live on nd.reshape)."""
    from ..ndarray.ndarray import apply_op as _apply

    def pure(v):
        shape = list(newshape) if not isinstance(newshape, int) else [newshape]
        src = list(v.shape)
        if reverse:
            out = npx_reshape_shape(src[::-1], shape[::-1])[::-1]
        else:
            out = npx_reshape_shape(src, shape)
        return v.reshape(out)

    return _apply(pure, a, name="reshape")


def batch_flatten(x):
    """Reference: npx.batch_flatten — collapse all but the batch axis."""
    from ..ndarray.ndarray import apply_op as _apply

    return _apply(lambda v: v.reshape(v.shape[0], -1), x,
                  name="batch_flatten")


def boolean_mask(data, index, axis=0):
    """Dynamic-output row selection (reference: _npi.boolean_mask,
    contrib/boolean_mask.cc — the dynamic-shape exemplar op). Eager
    index snapshot + differentiable gather; hybridized blocks
    containing it drop to imperative mode (CachedOp dynamic-shape)."""
    from ..contrib.ops import boolean_mask as _bm

    return _bm(data, index, axis=axis)


from ..ndarray.register import populate as _populate  # noqa: E402

_populate(globals())


def index_update(data, indices, val):
    """Functional scatter-set: data with data[indices] replaced by val
    (reference: _npx_index_update, src/operator/numpy/np_indexing_op.cc).
    Indices follow npx convention: an int array (N, ndim-prefix) of
    coordinates, or a plain index array for axis 0."""
    from ..ndarray.ndarray import apply_op

    def pure(x, idx, v):
        idx = _jnp.asarray(idx)
        if not (_jnp.issubdtype(idx.dtype, _jnp.integer)
                or idx.dtype == _jnp.bool_):  # bool masks pass through
            idx = idx.astype(_jnp.int32)  # f32 default-dtype indices
        if idx.ndim == 2 and idx.dtype != _jnp.bool_:  # coordinate rows
            return x.at[tuple(idx.T)].set(v)
        return x.at[idx].set(v)

    return apply_op(pure, data, indices, val, name="index_update")


def index_add(data, indices, val):
    """Functional scatter-add (reference: _npx_index_add)."""
    from ..ndarray.ndarray import apply_op

    def pure(x, idx, v):
        idx = _jnp.asarray(idx)
        if not (_jnp.issubdtype(idx.dtype, _jnp.integer)
                or idx.dtype == _jnp.bool_):  # bool masks pass through
            idx = idx.astype(_jnp.int32)  # f32 default-dtype indices
        if idx.ndim == 2 and idx.dtype != _jnp.bool_:
            return x.at[tuple(idx.T)].add(v)
        return x.at[idx].add(v)

    return apply_op(pure, data, indices, val, name="index_add")


def nonzero(data):
    """Indices of nonzero elements as an (N, ndim) int64 array
    (reference: _npx_nonzero). Eager: the output size is data-dependent."""
    arr = data.asnumpy() if hasattr(data, "asnumpy") else _onp.asarray(data)
    idx = _onp.stack(_onp.nonzero(arr), axis=-1) if arr.ndim else \
        _onp.zeros((0, 0), _onp.int64)
    return NDArray(_jnp.asarray(idx.astype(_onp.int64)))


def constraint_check(condition, msg="Constraint violated"):
    """Raise if any element is False, else return 1.0 (reference:
    _npx_constraint_check — the probability-module validation op)."""
    arr = condition.asnumpy() if hasattr(condition, "asnumpy") else \
        _onp.asarray(condition)
    if not bool(arr.all()):
        raise ValueError(msg)
    return NDArray(_jnp.ones((1,), _jnp.float32))


__all__ += ["index_update", "index_add", "nonzero", "constraint_check"]

from . import random  # noqa: F401,E402 - mx.npx.random namespace (last: needs bernoulli et al defined)
