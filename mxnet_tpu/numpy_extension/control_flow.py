"""Control-flow ops (reference: src/operator/control_flow.cc — npx.foreach,
npx.while_loop, npx.cond).

TPU-native: these lower to lax.scan / lax.while_loop / lax.cond so they are
traceable inside a hybridized block (the reference needed special stateful
CachedOp machinery; XLA control-flow HLOs replace it). Eager mode runs the
same lax ops immediately. Autograd flows through scan/cond via apply_op;
while_loop is forward-only (same as the reference, which has no
while_loop gradient).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ndarray.ndarray import NDArray, apply_op

__all__ = ["foreach", "while_loop", "cond"]


def _unwrap_tree(t):
    return jax.tree_util.tree_map(
        lambda a: a._data if isinstance(a, NDArray) else a, t,
        is_leaf=lambda a: isinstance(a, NDArray))


def _wrap_tree(t):
    return jax.tree_util.tree_map(NDArray, t)


def foreach(body, data, init_states):
    """Scan `body(x_t, states) -> (out_t, new_states)` over axis 0 of data.

    Reference: npx.foreach (control_flow.cc). Lowers to ONE lax.scan —
    XLA pipelines the loop; gradients supported (scan has a VJP).
    """
    multi_data = isinstance(data, (list, tuple))
    datas = list(data) if multi_data else [data]
    multi_state = isinstance(init_states, (list, tuple))
    states0 = list(init_states) if multi_state else [init_states]
    nd_inputs = datas + states0

    def fn(*flat):
        xs = flat[: len(datas)]
        st = list(flat[len(datas):])

        def step(carry, x_slices):
            x_in = [NDArray(s) for s in x_slices]
            s_in = [NDArray(c) for c in carry]
            out, new_states = body(
                x_in if multi_data else x_in[0],
                s_in if multi_state else s_in[0])
            outs = [o._data for o in (out if isinstance(out, (list, tuple))
                                      else [out])]
            ns = [s._data for s in (new_states
                                    if isinstance(new_states, (list, tuple))
                                    else [new_states])]
            return tuple(ns), tuple(outs)

        final, stacked = lax.scan(step, tuple(st), tuple(xs))
        return tuple(stacked) + tuple(final)

    result = apply_op(fn, *nd_inputs, name="foreach")
    if not isinstance(result, tuple):
        result = (result,)
    # count outputs by running shapes: outs come first, then states
    n_states = len(states0)
    outs = result[: len(result) - n_states]
    finals = result[len(result) - n_states:]
    out = outs if len(outs) > 1 else outs[0]
    fin = list(finals) if multi_state else finals[0]
    return out, fin


def while_loop(cond, func, loop_vars, max_iterations=None):
    """While loop (reference: npx.while_loop, python/mxnet contrib
    while_loop contract): `cond(*loop_vars) -> bool`,
    `func(*loop_vars) -> (step_output, new_loop_vars)`; returns
    `(outputs, final_loop_vars)` where outputs are stacked along a new
    first dim of size `max_iterations` (rows beyond the actual step count
    keep their initialized zeros, matching the reference's symbolic-mode
    padding). Forward-only, like the reference.
    """
    if max_iterations is None:
        raise ValueError("max_iterations is required (reference parity)")
    multi = isinstance(loop_vars, (list, tuple))
    lv = list(loop_vars) if multi else [loop_vars]
    datas = tuple(v._data if isinstance(v, NDArray) else jnp.asarray(v)
                  for v in lv)

    def run_cond(vars_):
        out = cond(*[NDArray(c) for c in vars_])
        return (out._data if isinstance(out, NDArray)
                else jnp.asarray(out)).reshape(()).astype(bool)

    def run_func(vars_):
        step_out, new_vars = func(*[NDArray(c) for c in vars_])
        if step_out is None:
            outs = []  # state-only loop (reference allows None outputs)
        elif isinstance(step_out, (list, tuple)):
            outs = list(step_out)
        else:
            outs = [step_out]
        nv = new_vars if isinstance(new_vars, (list, tuple)) else [new_vars]
        return (
            tuple(o._data if isinstance(o, NDArray) else jnp.asarray(o)
                  for o in outs),
            tuple(v._data if isinstance(v, NDArray) else jnp.asarray(v)
                  for v in nv),
        )

    # shapes of step outputs via abstract eval (no FLOPs)
    out_shapes = jax.eval_shape(lambda vs: run_func(vs)[0], datas)
    buffers = tuple(jnp.zeros((max_iterations,) + s.shape, s.dtype)
                    for s in out_shapes)

    def cond_fn(carry):
        i, vars_, _ = carry
        return jnp.logical_and(i < max_iterations, run_cond(vars_))

    def body_fn(carry):
        i, vars_, bufs = carry
        outs, new_vars = run_func(vars_)
        bufs = tuple(lax.dynamic_update_index_in_dim(b, o, i, 0)
                     for b, o in zip(bufs, outs))
        return i + 1, new_vars, bufs

    _, final_vars, bufs = lax.while_loop(
        cond_fn, body_fn, (jnp.int32(0), datas, buffers))
    outputs = [NDArray(b) for b in bufs]
    finals = [NDArray(f) for f in final_vars]
    # empty outputs stay a list, like the symbolic path (contrib.py)
    out = outputs if len(outputs) != 1 else outputs[0]
    fin = finals if multi else finals[0]
    return out, fin


def cond(pred, then_func, else_func, inputs=()):
    """Conditional (reference: npx.cond). Both branches traced; XLA picks at
    run time — differentiable."""
    ins = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    nd_inputs = [pred] + ins

    def fn(p, *xs):
        p_bool = p.reshape(()).astype(bool)

        def tb(args):
            out = then_func(*[NDArray(a) for a in args])
            return _unwrap_tree(out)

        def eb(args):
            out = else_func(*[NDArray(a) for a in args])
            return _unwrap_tree(out)

        return lax.cond(p_bool, tb, eb, tuple(xs))

    return apply_op(fn, *nd_inputs, name="cond")
