"""`mx.npx.image` namespace (reference: mxnet/numpy_extension/image.py)
— one surface with mx.nd.image (see ndarray/image.py)."""
from ..ndarray.image import __all__, __dir__, __getattr__  # noqa: F401
