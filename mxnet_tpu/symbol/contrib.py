"""`mx.sym.contrib` namespace (reference: mxnet/symbol/contrib.py).

Two populations, same as the reference file: the contrib op corpus (the
generic symbol-op mechanism covers every registered contrib op), and the
hand-written *symbolic control flow* — foreach:212, while_loop:375,
cond:598.

TPU re-design of control flow: the reference cuts the body into an nnvm
subgraph and ships it to a specialized C++ op (control_flow.cc). Here the
body is traced into a sub-Symbol whose JSON is stored as a node attr, and
the node's lowering rebuilds the subgraph and wraps it in lax.scan /
lax.while_loop / lax.cond — so a serialized graph (tojson/save) carries
its loops, and XLA compiles them as native control-flow HLOs.
"""
import json as _json

import jax
import jax.numpy as jnp
from jax import lax

from ..contrib.ops import *  # noqa: F401,F403
from ..contrib.ops import __all__ as _ops_all
from .symbol import Group, Symbol, fromjson, register_sym_op, var

__all__ = list(_ops_all) + ["foreach", "while_loop", "cond"]

_SUBGRAPH_CACHE = {}  # json string -> lowered fn (avoid re-parse per trace)


def _lowered(js):
    fn = _SUBGRAPH_CACHE.get(js)
    if fn is None:
        fn = _SUBGRAPH_CACHE[js] = fromjson(js)._lower()
    return fn


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _capture_leaves(sub, bound_names):
    """Free variables of a subgraph besides the bound loop inputs.

    When the body closes over outer symbols, their whole subtrees are part
    of the traced sub-DAG (shared Symbol identity); the loop node must
    take those subtrees' leaf variables as its own graph inputs. Loop-
    invariant recomputation inside the body is fine: XLA hoists invariant
    computations out of scan/while bodies.
    """
    caps, names = [], []
    for s in sub._topo():
        if s._op is None and s._name not in bound_names \
                and s._name not in names:
            caps.append(s)
            names.append(s._name)
    return caps, names


def foreach(body, data, init_states, name="foreach"):
    """Symbolic scan (reference: symbol/contrib.py:212). `body(data_slice,
    states) -> (step_output, new_states)` traced once into a subgraph;
    lowers to ONE lax.scan."""
    multi_data = isinstance(data, (list, tuple))
    datas = _as_list(data)
    multi_state = isinstance(init_states, (list, tuple))
    states = _as_list(init_states)

    # bound names must be unique per CALL, not per user-visible name —
    # nested loops with the default name would otherwise collide inside
    # one subgraph and silently shadow captured outer values
    uniq = Symbol._auto_name(f"__{name}")
    data_vars = [var(f"{uniq}_data{i}") for i in range(len(datas))]
    state_vars = [var(f"{uniq}_state{i}") for i in range(len(states))]
    out, new_states = body(data_vars if multi_data else data_vars[0],
                           state_vars if multi_state else state_vars[0])
    outs = _as_list(out)
    nss = _as_list(new_states)
    if len(nss) != len(states):
        raise ValueError(
            f"body returned {len(nss)} states, expected {len(states)}")
    sub = Group(outs + nss)
    bound = [v._name for v in data_vars + state_vars]
    caps, cap_names = _capture_leaves(sub, set(bound))
    node = Symbol.create(
        "_foreach", *(datas + states + caps), name=name,
        nout=len(outs) + len(nss),
        subgraph=sub.tojson(),
        in_names=_json.dumps(bound + cap_names),
        num_data=len(datas), num_states=len(states),
        num_outputs=len(outs))
    flat = node._flat_outputs()
    o, f = flat[:len(outs)], flat[len(outs):]
    return (o if len(o) > 1 else o[0],
            f if multi_state else f[0])


def _foreach_lower(ins, attrs):
    subfn = _lowered(attrs["subgraph"])
    names = _json.loads(attrs["in_names"])
    n_d, n_s = attrs["num_data"], attrs["num_states"]
    n_o = attrs["num_outputs"]
    xs = tuple(ins[:n_d])
    carry0 = tuple(ins[n_d:n_d + n_s])
    cap = dict(zip(names[n_d + n_s:], ins[n_d + n_s:]))

    def step(carry, x):
        d = dict(zip(names[:n_d], x))
        d.update(zip(names[n_d:n_d + n_s], carry))
        d.update(cap)
        res = subfn(d)
        return tuple(res[n_o:]), tuple(res[:n_o])

    final, stacked = lax.scan(step, carry0, xs)
    out = tuple(stacked) + tuple(final)
    return out if len(out) > 1 else out[0]


register_sym_op("_foreach", _foreach_lower)


def while_loop(cond, func, loop_vars, max_iterations=None,
               name="while_loop"):
    """Symbolic while (reference: symbol/contrib.py:375). Outputs are
    stacked into `max_iterations` rows (rows past the real step count keep
    zeros — the reference leaves them uninitialized); forward-only, like
    the reference."""
    if max_iterations is None:
        raise ValueError("max_iterations is required")
    multi = isinstance(loop_vars, (list, tuple))
    lvs = _as_list(loop_vars)
    uniq = Symbol._auto_name(f"__{name}")
    lv_vars = [var(f"{uniq}_var{i}") for i in range(len(lvs))]

    cond_sym = cond(*lv_vars)
    step_out, new_vars = func(*lv_vars)
    outs = _as_list(step_out) if step_out is not None else []
    nvs = _as_list(new_vars)
    if len(nvs) != len(lvs):
        raise ValueError("func must return one new var per loop var")
    sub = Group([cond_sym] + outs + nvs)
    bound = [v._name for v in lv_vars]
    caps, cap_names = _capture_leaves(sub, set(bound))
    node = Symbol.create(
        "_while_loop", *(lvs + caps), name=name,
        nout=len(outs) + len(nvs),
        subgraph=sub.tojson(),
        in_names=_json.dumps(bound + cap_names),
        num_vars=len(lvs), num_outputs=len(outs),
        max_iterations=int(max_iterations))
    flat = node._flat_outputs()
    o, f = flat[:len(outs)], flat[len(outs):]
    return (o if len(o) != 1 else o[0], f if multi else f[0])


def _while_lower(ins, attrs):
    subfn = _lowered(attrs["subgraph"])
    names = _json.loads(attrs["in_names"])
    n_v, n_o = attrs["num_vars"], attrs["num_outputs"]
    max_it = attrs["max_iterations"]
    vars0 = tuple(ins[:n_v])
    cap = dict(zip(names[n_v:], ins[n_v:]))

    def run(vars_):
        d = dict(zip(names[:n_v], vars_))
        d.update(cap)
        res = subfn(d)
        pred = jnp.reshape(res[0], ()).astype(bool)
        return pred, tuple(res[1:1 + n_o]), tuple(res[1 + n_o:])

    out_shapes = jax.eval_shape(lambda vs: run(vs)[1], vars0)
    bufs0 = tuple(jnp.zeros((max_it,) + s.shape, s.dtype)
                  for s in out_shapes)

    def cond_fn(carry):
        i, vars_, _ = carry
        return jnp.logical_and(i < max_it, run(vars_)[0])

    def body_fn(carry):
        i, vars_, bufs = carry
        _, outs, new_vars = run(vars_)
        bufs = tuple(lax.dynamic_update_index_in_dim(b, o, i, 0)
                     for b, o in zip(bufs, outs))
        return i + 1, new_vars, bufs

    _, final, bufs = lax.while_loop(
        cond_fn, body_fn, (jnp.int32(0), vars0, bufs0))
    out = tuple(bufs) + tuple(final)
    return out if len(out) > 1 else out[0]


register_sym_op("_while_loop", _while_lower)


def cond(pred, then_func, else_func, name="cond"):
    """Symbolic conditional (reference: symbol/contrib.py:598). then/else
    take no arguments (they close over outer symbols); both branches trace
    into subgraphs; lowers to lax.cond — XLA picks at run time."""
    then_sym = Group(_as_list(then_func()))
    else_sym = Group(_as_list(else_func()))
    n_then = len(then_sym._inputs)
    n_else = len(else_sym._inputs)
    if n_then != n_else:
        raise ValueError(
            f"then ({n_then}) and else ({n_else}) output counts differ")
    t_caps, t_names = _capture_leaves(then_sym, set())
    e_caps, e_names = _capture_leaves(else_sym, set())
    node = Symbol.create(
        "_cond", pred, *(t_caps + e_caps), name=name, nout=n_then,
        then_graph=then_sym.tojson(), else_graph=else_sym.tojson(),
        then_names=_json.dumps(t_names), else_names=_json.dumps(e_names))
    flat = node._flat_outputs()
    return flat if len(flat) > 1 else flat[0]


def _cond_lower(ins, attrs):
    then_fn = _lowered(attrs["then_graph"])
    else_fn = _lowered(attrs["else_graph"])
    t_names = _json.loads(attrs["then_names"])
    e_names = _json.loads(attrs["else_names"])
    pred = jnp.reshape(ins[0], ()).astype(bool)
    t_ins = dict(zip(t_names, ins[1:1 + len(t_names)]))
    e_ins = dict(zip(e_names, ins[1 + len(t_names):]))
    out = lax.cond(pred,
                   lambda d: tuple(then_fn(d[0])),
                   lambda d: tuple(else_fn(d[1])),
                   (t_ins, e_ins))
    return out if len(out) > 1 else out[0]


register_sym_op("_cond", _cond_lower)


_zipfian_node_counter = [0]


def rand_zipfian(true_classes, num_sampled, range_max, seed=None):
    """Zipfian (log-uniform) candidate sampler, symbol form (reference:
    python/mxnet/symbol/contrib.py:35 — a python composite over symbol
    primitives there as well). P(class) = (log(class+2) - log(class+1))
    / log(range_max+1). Returns (sampled int64 symbol,
    expected_count_true, expected_count_sampled).

    Symbol random nodes are pure functions of (shape, seed) — see
    symbol/random.py. With seed=None each rand_zipfian call gets a fresh
    construction-time seed, so two sampled-softmax heads in one graph
    draw different candidate sets; pass an explicit seed to pin it."""
    import math as _math

    from .. import symbol as _S  # fully initialized at call time

    if seed is None:
        seed = _zipfian_node_counter[0]
        _zipfian_node_counter[0] += 1
    log_range = _math.log(range_max + 1)
    rand = _S.random.uniform(low=0.0, high=log_range, shape=(num_sampled,),
                             dtype="float64", seed=seed)
    sampled = _S.cast(_S.exp(rand) - 1.0, dtype="int64") % range_max

    true_f = _S.cast(true_classes, dtype="float64")
    cnt_true = _S.log((true_f + 2.0) / (true_f + 1.0)) \
        / log_range * num_sampled
    sampled_f = _S.cast(sampled, dtype="float64")
    cnt_sampled = _S.log((sampled_f + 2.0) / (sampled_f + 1.0)) \
        / log_range * num_sampled
    return sampled, cnt_true, cnt_sampled
