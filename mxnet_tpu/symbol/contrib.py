"""`mx.sym.contrib` namespace (reference: mxnet/symbol/contrib.py).
Eager contrib implementations double as symbol-graph builders through the
generic symbol op mechanism where registered; unregistered names raise."""
from ..contrib.ops import *  # noqa: F401,F403
from ..contrib.ops import __all__  # noqa: F401
