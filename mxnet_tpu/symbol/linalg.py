"""`mx.sym.linalg` namespace (reference: mxnet/symbol/linalg.py — the
la_op family as symbol builders). Short names map onto the registered
`linalg_*` table entries, so `mx.sym.linalg.potrf(A)` builds the same
graph node `mx.sym.linalg_...` lowering uses."""
from __future__ import annotations

from .op_extended import _LINALG_NOUT
from .symbol import _OP_TABLE, Symbol

__all__ = []  # populated below


def _make(short, full):
    nout = _LINALG_NOUT.get(full, 1)

    def wrapper(*inputs, name=None, **attrs):
        return Symbol.create(full, *inputs, name=name, nout=nout, **attrs)

    wrapper.__name__ = short
    wrapper.__doc__ = f"Symbol builder for {full} (reference: la_op.cc)."
    return wrapper


def _populate():
    g = globals()
    for opname in sorted(_OP_TABLE):
        if opname.startswith("linalg_"):
            short = opname[len("linalg_"):]
            g[short] = _make(short, opname)
            __all__.append(short)


_populate()
