"""Generate the full mx.sym op namespace from the operator registry.

Reference: python/mxnet/symbol/register.py:1-291 — the reference code-gens a
Python builder for every op NNVM registered, so `mx.sym` always covers the
whole op corpus. Round 2 hand-curated 196 symbol ops; anything outside the
table couldn't be expressed, exported, or re-imported symbolically
(VERDICT r2 missing #1). This module closes the gap the same way the
`mx.nd`/`mx.npx` namespaces already do: every `ops.registry` entry gets

  1. a lowering adapter in the symbol op table — `fn(*inputs, **attrs)`
     over the SAME pure-jax implementation the imperative frontends call,
     so symbolic == imperative numerically by construction, and
  2. a builder exposed as `mx.sym.<name>` (via the package __getattr__),
     accepting inputs positionally or as named kwargs (`data=`, `weight=`)
     exactly like reference generated code.

Hand-curated wrappers in op.py / op_extended.py keep priority — they encode
legacy quirks (SoftmaxOutput's grad scaling, split's nout) that a generic
adapter can't.
"""
from __future__ import annotations

import inspect

from ..ops import registry as _registry
from ..ops.rnn import _battr
from .symbol import _OP_TABLE, Symbol, register_sym_op

# ops whose output count depends on attrs (generic adapters default to 1;
# these need Symbol.nout to match so __getitem__/list_outputs work)
def _three(a):  # noqa: ARG001 - quantized ops return (out, min, max)
    return 3


_MULTI_OUT = {
    "_contrib_quantize": _three,
    "_contrib_quantize_v2": _three,
    "_contrib_requantize": _three,
    "_contrib_quantized_conv": _three,
    "_contrib_quantized_fully_connected": _three,
    "_contrib_quantized_pooling": _three,
    "_contrib_quantized_act": _three,
    "_contrib_quantized_flatten": _three,
    "_contrib_quantized_batch_norm": _three,
    "_contrib_quantized_elemwise_add": _three,
    "_contrib_quantized_elemwise_mul": _three,
    "_contrib_quantized_concat": _three,
    "_contrib_quantized_embedding": _three,
    "_contrib_bipartite_matching": lambda a: 2,
    "_contrib_box_encode": lambda a: 2,
    "_contrib_MultiBoxTarget": lambda a: 3,
    # registered as jnp.split: int = n equal sections, seq = cut points
    "_split_v2": lambda a: (
        len(a["indices_or_sections"]) + 1
        if isinstance(a.get("indices_or_sections"), (tuple, list))
        else int(a.get("indices_or_sections", 1))),
    # fused RNN: out [+ state_h [+ state_cell for lstm]] (rnn.cc);
    # boolean parsing MUST match ops.rnn._battr or nout lies about the
    # lowered tuple arity
    "RNN": lambda a: (
        (3 if str(a.get("mode", "lstm")) == "lstm" else 2)
        if _battr(a.get("state_outputs", False)) else 1),
    "_sample_multinomial": lambda a: (
        2 if _battr(a.get("get_prob", False)) else 1),
    "histogram": lambda a: 2,
}


def _tensor_param_names(fn):
    """Positional parameter names of the registered pure function — the
    op's tensor-input slots, in order (attrs are keyword-only or trailing
    defaults) — plus the set of REQUIRED (no-default) names, which is
    what gates nnvm-style auto-param creation."""
    try:
        params = [p for p in inspect.signature(fn).parameters.values()
                  if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                                inspect.Parameter.POSITIONAL_OR_KEYWORD)]
    except (ValueError, TypeError):
        return [], frozenset()
    names = [p.name for p in params]
    required = frozenset(p.name for p in params
                         if p.default is inspect.Parameter.empty)
    return names, required


def _unwrap_tree(x):
    """Some registry entries are imperative apply_op wrappers that return
    NDArrays (e.g. the quantized family) — lowering must hand raw jax
    arrays back to the graph so jax.eval_shape/jit can trace them."""
    from ..ndarray.ndarray import NDArray

    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (tuple, list)):
        return type(x)(_unwrap_tree(v) for v in x)
    return x


def _make_lowering(fn):
    def lower(ins, attrs):
        return _unwrap_tree(fn(*ins, **attrs))

    return lower


def _quantized_no_bias_lowering(fn):
    """quantized conv/FC take (data, weight, bias, ranges...) positionally;
    a no_bias graph has no bias INPUT, so re-bind with bias=None."""
    def lower(ins, attrs):
        if attrs.get("no_bias") in (True, 1, "True", "1") and len(ins) == 6:
            d, w, dlo, dhi, wlo, whi = ins
            return _unwrap_tree(fn(d, w, None, dlo, dhi, wlo, whi,
                                   **attrs))
        return _unwrap_tree(fn(*ins, **attrs))

    return lower


_SPECIAL_LOWERING = {
    "_contrib_quantized_conv": _quantized_no_bias_lowering,
    "_contrib_quantized_fully_connected": _quantized_no_bias_lowering,
}


# parameter slots nnvm auto-creates as variables when a symbol op is
# called without them (reference: symbol composition names them
# {opname}_{slot} — mx.sym.Convolution(data=d, ...) materializes
# conv_weight/conv_bias; test_attr.py expects the __dunder__ annotation
# attrs to propagate onto them)
_AUTO_PARAM_SLOTS = frozenset(
    {"weight", "bias", "gamma", "beta", "moving_mean", "moving_var",
     "parameters", "state", "state_cell"})


def _make_builder(op_name, pos_names, required=frozenset()):
    def _auto_allowed(slot, kwargs):
        """nnvm-style composition creates a variable for a missing slot
        only when the op genuinely consumes it: the slot is a
        parameter-style name, REQUIRED by the signature (optional slots
        like prelu's gamma stay absent), and not disabled by an attr."""
        if slot not in _AUTO_PARAM_SLOTS or slot not in required:
            return False
        if slot == "bias" and _battr(kwargs.get("no_bias", False)):
            return False
        if slot == "state_cell" \
                and str(kwargs.get("mode", "lstm")) != "lstm":
            return False  # RNN: cell state is an input only for lstm
        return True

    def builder(*inputs, name=None, **kwargs):
        # a None tensor slot means "input absent" (reference convention:
        # e.g. bias with no_bias=True) — drop it rather than making an
        # object-dtype constant
        inputs = [i for i in inputs if i is not None]
        for k in [k for k, v in kwargs.items()
                  if v is None and k in pos_names]:
            kwargs.pop(k)
        # place operands into their signature slots: positionals fill a
        # prefix, named tensor kwargs land at their named slot (gaps in
        # between auto-create, so batch_norm(d, beta=b) keeps beta in
        # the beta slot instead of silently occupying gamma)
        slots = {}
        for i, v in enumerate(inputs):
            if i < len(pos_names):
                slots[pos_names[i]] = v
            else:
                slots[f"#extra{i}"] = v  # varargs ops (add_n)
        extra_named = []
        for k in [k for k, v in kwargs.items() if isinstance(v, Symbol)]:
            if k in pos_names:
                slots[k] = kwargs.pop(k)
            else:
                # reference spellings sometimes differ from our signature
                # names (sym.histogram(a=...)); unknown-named symbol
                # operands fill remaining slots in call order
                extra_named.append(kwargs.pop(k))
        filled_idx = [pos_names.index(k) for k in slots if k in pos_names]
        last = max(filled_idx, default=-1)
        ordered, auto_needed = [], []
        for i, slot in enumerate(pos_names):
            if slot in slots:
                ordered.append(slots[slot])
                continue
            if i < last:
                if not _auto_allowed(slot, kwargs):
                    raise ValueError(
                        f"{op_name}: input {slot!r} missing but a later "
                        f"slot was provided; pass {slot!r} explicitly")
                auto_needed.append((len(ordered), slot))
                ordered.append(None)
            elif _auto_allowed(slot, kwargs):
                auto_needed.append((len(ordered), slot))
                ordered.append(None)
            else:
                break
        ordered.extend(v for k, v in slots.items()
                       if k.startswith("#extra"))
        ordered.extend(extra_named)
        if auto_needed:
            # one shared composition helper with the CamelCase builders
            # (op.py) — annotation source includes lr_mult-style kwargs,
            # not just the attr= dict
            from . import op as _op_mod

            final_name = _op_mod._resolve_name(name, op_name.lower())
            name = final_name
            user = dict(kwargs.get("attr", None) or {})
            user.update({k: kwargs[k] for k in kwargs
                         if k in Symbol._MIRROR_KEYS})
            for pos, slot in auto_needed:
                ordered[pos] = _op_mod._auto_param(final_name, slot, user)
        inputs = [v for v in ordered if v is not None]
        nout = _MULTI_OUT.get(op_name, lambda a: 1)(kwargs)
        return Symbol.create(op_name, *inputs, name=name, nout=nout,
                             **kwargs)

    builder.__name__ = op_name
    builder.__qualname__ = op_name
    builder.__doc__ = (f"Symbol builder for registered op `{op_name}` "
                       "(generated from the op registry; lowers to the "
                       "same jax implementation as the imperative op).")
    return builder


_GENERATED = {}


def _generate():
    for op_name in _registry.list_ops():
        fn = _registry.get_op(op_name)
        if op_name not in _OP_TABLE:
            make = _SPECIAL_LOWERING.get(op_name, _make_lowering)
            register_sym_op(op_name, make(fn))
        if op_name not in _GENERATED:
            _names, _req = _tensor_param_names(fn)
            _GENERATED[op_name] = _make_builder(op_name, _names, _req)


_generate()


def get_builder(name):
    """Builder for `name`, regenerating if the registry grew (custom ops
    registered after import)."""
    if name not in _GENERATED and name in _registry._OPS:
        _generate()
    return _GENERATED.get(name)


def list_generated():
    return sorted(_GENERATED)
