"""Generate the full mx.sym op namespace from the operator registry.

Reference: python/mxnet/symbol/register.py:1-291 — the reference code-gens a
Python builder for every op NNVM registered, so `mx.sym` always covers the
whole op corpus. Round 2 hand-curated 196 symbol ops; anything outside the
table couldn't be expressed, exported, or re-imported symbolically
(VERDICT r2 missing #1). This module closes the gap the same way the
`mx.nd`/`mx.npx` namespaces already do: every `ops.registry` entry gets

  1. a lowering adapter in the symbol op table — `fn(*inputs, **attrs)`
     over the SAME pure-jax implementation the imperative frontends call,
     so symbolic == imperative numerically by construction, and
  2. a builder exposed as `mx.sym.<name>` (via the package __getattr__),
     accepting inputs positionally or as named kwargs (`data=`, `weight=`)
     exactly like reference generated code.

Hand-curated wrappers in op.py / op_extended.py keep priority — they encode
legacy quirks (SoftmaxOutput's grad scaling, split's nout) that a generic
adapter can't.
"""
from __future__ import annotations

import inspect

from ..ops import registry as _registry
from ..ops.rnn import _battr
from .symbol import _OP_TABLE, Symbol, register_sym_op

# ops whose output count depends on attrs (generic adapters default to 1;
# these need Symbol.nout to match so __getitem__/list_outputs work)
def _three(a):  # noqa: ARG001 - quantized ops return (out, min, max)
    return 3


_MULTI_OUT = {
    "_contrib_quantize": _three,
    "_contrib_quantize_v2": _three,
    "_contrib_requantize": _three,
    "_contrib_quantized_conv": _three,
    "_contrib_quantized_fully_connected": _three,
    "_contrib_quantized_pooling": _three,
    "_contrib_quantized_act": _three,
    "_contrib_quantized_flatten": _three,
    "_contrib_quantized_batch_norm": _three,
    "_contrib_quantized_elemwise_add": _three,
    "_contrib_quantized_elemwise_mul": _three,
    "_contrib_quantized_concat": _three,
    "_contrib_quantized_embedding": _three,
    "_contrib_bipartite_matching": lambda a: 2,
    "_contrib_box_encode": lambda a: 2,
    "_contrib_MultiBoxTarget": lambda a: 3,
    # registered as jnp.split: int = n equal sections, seq = cut points
    "_split_v2": lambda a: (
        len(a["indices_or_sections"]) + 1
        if isinstance(a.get("indices_or_sections"), (tuple, list))
        else int(a.get("indices_or_sections", 1))),
    # fused RNN: out [+ state_h [+ state_cell for lstm]] (rnn.cc);
    # boolean parsing MUST match ops.rnn._battr or nout lies about the
    # lowered tuple arity
    "RNN": lambda a: (
        (3 if str(a.get("mode", "lstm")) == "lstm" else 2)
        if _battr(a.get("state_outputs", False)) else 1),
    "_sample_multinomial": lambda a: (
        2 if _battr(a.get("get_prob", False)) else 1),
    "histogram": lambda a: 2,
}


def _tensor_param_names(fn):
    """Positional parameter names of the registered pure function — the
    op's tensor-input slots, in order (attrs are keyword-only or trailing
    defaults)."""
    try:
        params = inspect.signature(fn).parameters.values()
    except (ValueError, TypeError):
        return []
    return [p.name for p in params
            if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                          inspect.Parameter.POSITIONAL_OR_KEYWORD)]


def _unwrap_tree(x):
    """Some registry entries are imperative apply_op wrappers that return
    NDArrays (e.g. the quantized family) — lowering must hand raw jax
    arrays back to the graph so jax.eval_shape/jit can trace them."""
    from ..ndarray.ndarray import NDArray

    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (tuple, list)):
        return type(x)(_unwrap_tree(v) for v in x)
    return x


def _make_lowering(fn):
    def lower(ins, attrs):
        return _unwrap_tree(fn(*ins, **attrs))

    return lower


def _quantized_no_bias_lowering(fn):
    """quantized conv/FC take (data, weight, bias, ranges...) positionally;
    a no_bias graph has no bias INPUT, so re-bind with bias=None."""
    def lower(ins, attrs):
        if attrs.get("no_bias") in (True, 1, "True", "1") and len(ins) == 6:
            d, w, dlo, dhi, wlo, whi = ins
            return _unwrap_tree(fn(d, w, None, dlo, dhi, wlo, whi,
                                   **attrs))
        return _unwrap_tree(fn(*ins, **attrs))

    return lower


_SPECIAL_LOWERING = {
    "_contrib_quantized_conv": _quantized_no_bias_lowering,
    "_contrib_quantized_fully_connected": _quantized_no_bias_lowering,
}


def _make_builder(op_name, pos_names):
    def builder(*inputs, name=None, **kwargs):
        # a None tensor slot means "input absent" (reference convention:
        # e.g. bias with no_bias=True) — drop it rather than making an
        # object-dtype constant
        inputs = [i for i in inputs if i is not None]
        for k in [k for k, v in kwargs.items()
                  if v is None and k in pos_names]:
            kwargs.pop(k)
        # named tensor inputs (data=x, weight=w) go to their signature
        # slots, in signature order after any positional inputs
        named = [(k, v) for k, v in kwargs.items() if isinstance(v, Symbol)]
        for k, _ in named:
            kwargs.pop(k)
        named.sort(key=lambda kv: pos_names.index(kv[0])
                   if kv[0] in pos_names else len(pos_names))
        inputs.extend(v for _, v in named)
        nout = _MULTI_OUT.get(op_name, lambda a: 1)(kwargs)
        return Symbol.create(op_name, *inputs, name=name, nout=nout,
                             **kwargs)

    builder.__name__ = op_name
    builder.__qualname__ = op_name
    builder.__doc__ = (f"Symbol builder for registered op `{op_name}` "
                       "(generated from the op registry; lowers to the "
                       "same jax implementation as the imperative op).")
    return builder


_GENERATED = {}


def _generate():
    for op_name in _registry.list_ops():
        fn = _registry.get_op(op_name)
        if op_name not in _OP_TABLE:
            make = _SPECIAL_LOWERING.get(op_name, _make_lowering)
            register_sym_op(op_name, make(fn))
        if op_name not in _GENERATED:
            _GENERATED[op_name] = _make_builder(
                op_name, _tensor_param_names(fn))


_generate()


def get_builder(name):
    """Builder for `name`, regenerating if the registry grew (custom ops
    registered after import)."""
    if name not in _GENERATED and name in _registry._OPS:
        _generate()
    return _GENERATED.get(name)


def list_generated():
    return sorted(_GENERATED)
