"""`mx.sym.image` namespace (reference: mxnet/symbol/image.py — the
`_image_*` op family under short names, `gen_image`)."""
from . import register as _register

__all__ = ["resize", "crop", "to_tensor", "normalize", "random_crop",
           "random_resized_crop"]


def resize(src, size=None, keep_ratio=False, interp=1):
    """Symbolic resize with the reference signature (size int/(w,h));
    keep_ratio needs the input extent, which a lazy graph doesn't know, so
    it requires an explicit (w, h) — same restriction as the reference's
    symbolic path for data-dependent sizes."""
    if size is None:
        raise ValueError("resize requires size")
    if isinstance(size, int):
        if keep_ratio:
            raise ValueError("symbolic resize with keep_ratio needs an "
                             "explicit (w, h) size (input extent is not "
                             "known at graph-build time)")
        size = (size, size)
    w, h = size
    return _register.get_builder("_image_resize")(src, w, h, interp=interp)


def __getattr__(name):
    builder = _register.get_builder(f"_image_{name}")
    if builder is not None:
        return builder
    raise AttributeError(f"mx.sym.image has no op {name!r}")


def __dir__():
    return sorted(__all__)
