"""Extended symbol operator table: math tail, comparisons, indexing,
ordering, sequence ops, norms, shape utilities.

Reference: the generated mx.sym.* corpus (symbol/register.py over the NNVM
registry — 595 names). This module grows the symbol vocabulary to cover
the reference's high-traffic graph ops so attention models (BERT) and the
vision zoo can be expressed/round-tripped symbolically and exported to
ONNX. Every op lowers to the same jnp implementations the imperative
frontends use, so symbolic == imperative numerically by construction.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..ops import nn as _nn
from .symbol import Symbol, register_sym_op

__all__ = []


def _reg(name, **defaults):
    """Register a lowering + return a Symbol-building wrapper (same
    pattern as op.py, with default attrs)."""
    def deco(fn):
        register_sym_op(name, fn)

        def wrapper(*inputs, name=None, **attrs):  # noqa: A002
            merged = dict(defaults)
            merged.update(attrs)
            return Symbol.create(op_name, *inputs, name=name, **merged)

        op_name = fn_name
        wrapper.__name__ = name
        __all__.append(name)
        return wrapper

    fn_name = name
    return deco


def _f(jfn):
    return lambda ins, a: jfn(ins[0])


# -- unary math tail --------------------------------------------------------
sin = _reg("sin")(_f(jnp.sin))
cos = _reg("cos")(_f(jnp.cos))
tan = _reg("tan")(_f(jnp.tan))
arcsin = _reg("arcsin")(_f(jnp.arcsin))
arccos = _reg("arccos")(_f(jnp.arccos))
arctan = _reg("arctan")(_f(jnp.arctan))
sinh = _reg("sinh")(_f(jnp.sinh))
cosh = _reg("cosh")(_f(jnp.cosh))
arcsinh = _reg("arcsinh")(_f(jnp.arcsinh))
arccosh = _reg("arccosh")(_f(jnp.arccosh))
arctanh = _reg("arctanh")(_f(jnp.arctanh))
degrees = _reg("degrees")(_f(jnp.degrees))
radians = _reg("radians")(_f(jnp.radians))
floor = _reg("floor")(_f(jnp.floor))
ceil = _reg("ceil")(_f(jnp.ceil))
round = _reg("round")(_f(jnp.round))  # noqa: A001
rint = _reg("rint")(_f(jnp.rint))
trunc = _reg("trunc")(_f(jnp.trunc))
fix = _reg("fix")(_f(jnp.trunc))  # fix == trunc toward zero
sign = _reg("sign")(_f(jnp.sign))
reciprocal = _reg("reciprocal")(_f(lambda x: 1.0 / x))
rsqrt = _reg("rsqrt")(_f(lax.rsqrt))
cbrt = _reg("cbrt")(_f(jnp.cbrt))
rcbrt = _reg("rcbrt")(_f(lambda x: 1.0 / jnp.cbrt(x)))
expm1 = _reg("expm1")(_f(jnp.expm1))
log1p = _reg("log1p")(_f(jnp.log1p))
log2 = _reg("log2")(_f(jnp.log2))
log10 = _reg("log10")(_f(jnp.log10))
erf = _reg("erf")(_f(lax.erf))
erfinv = _reg("erfinv")(_f(lax.erf_inv))
gamma = _reg("gamma")(_f(lambda x: jnp.exp(lax.lgamma(x))))
gammaln = _reg("gammaln")(_f(lax.lgamma))
logical_not = _reg("logical_not")(
    _f(lambda x: (~x.astype(bool)).astype(jnp.float32)))
softsign = _reg("softsign")(_f(lambda x: x / (1 + jnp.abs(x))))
hard_sigmoid = _reg("hard_sigmoid")(
    lambda ins, a: jnp.clip(ins[0] * a.get("alpha", 0.2)
                            + a.get("beta", 0.5), 0, 1))

# -- binary / comparison (broadcast semantics: jnp broadcasts) --------------
_b = {
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_power": jnp.power,
    "broadcast_mod": jnp.mod,
    "mod": jnp.mod,
    "broadcast_hypot": jnp.hypot,
    "broadcast_equal": lambda x, y: (x == y).astype(jnp.float32),
    "broadcast_not_equal": lambda x, y: (x != y).astype(jnp.float32),
    "broadcast_greater": lambda x, y: (x > y).astype(jnp.float32),
    "broadcast_greater_equal": lambda x, y: (x >= y).astype(jnp.float32),
    "broadcast_lesser": lambda x, y: (x < y).astype(jnp.float32),
    "broadcast_lesser_equal": lambda x, y: (x <= y).astype(jnp.float32),
    "broadcast_logical_and": lambda x, y: (
        x.astype(bool) & y.astype(bool)).astype(jnp.float32),
    "broadcast_logical_or": lambda x, y: (
        x.astype(bool) | y.astype(bool)).astype(jnp.float32),
    "broadcast_logical_xor": lambda x, y: (
        x.astype(bool) ^ y.astype(bool)).astype(jnp.float32),
}
for _name, _jfn in _b.items():
    globals()[_name] = _reg(_name)(
        lambda ins, a, _j=_jfn: _j(ins[0], ins[1]))

# -- reductions tail --------------------------------------------------------


def _axis(a):
    ax = a.get("axis")
    return tuple(ax) if isinstance(ax, list) else ax


nansum = _reg("nansum")(
    lambda ins, a: jnp.nansum(ins[0], axis=_axis(a),
                              keepdims=a.get("keepdims", False)))
nanprod = _reg("nanprod")(
    lambda ins, a: jnp.nanprod(ins[0], axis=_axis(a),
                               keepdims=a.get("keepdims", False)))
logsumexp = _reg("logsumexp")(
    lambda ins, a: jax_logsumexp(ins[0], _axis(a),
                                 a.get("keepdims", False)))


def jax_logsumexp(x, axis, keepdims):
    m = jnp.max(x, axis=axis, keepdims=True)
    out = jnp.log(jnp.sum(jnp.exp(x - m), axis=axis, keepdims=True)) + m
    return out if keepdims else jnp.squeeze(
        out, axis if axis is not None else None)


argmax_channel = _reg("argmax_channel")(
    lambda ins, a: jnp.argmax(ins[0], axis=1).astype(jnp.float32))

# -- dtype / shape utilities ------------------------------------------------
cast = _reg("Cast")(lambda ins, a: ins[0].astype(a["dtype"]))
Cast = cast
__all__.append("Cast")
shape_array = _reg("shape_array")(
    lambda ins, a: jnp.asarray(ins[0].shape, jnp.int64))
size_array = _reg("size_array")(
    lambda ins, a: jnp.asarray([ins[0].size], jnp.int64))
tile = _reg("tile")(lambda ins, a: jnp.tile(ins[0], tuple(a["reps"])))
repeat = _reg("repeat")(
    lambda ins, a: jnp.repeat(ins[0], a["repeats"], axis=a.get("axis")))
flip = _reg("flip")(lambda ins, a: jnp.flip(ins[0], axis=a.get("axis")))
reverse = _reg("reverse")(
    lambda ins, a: jnp.flip(ins[0], axis=a.get("axis")))


def _pad_impl(ins, a):
    mode = a.get("mode", "constant")
    pw = a["pad_width"]
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    if mode == "constant":
        return jnp.pad(ins[0], pairs,
                       constant_values=a.get("constant_value", 0.0))
    return jnp.pad(ins[0], pairs, mode="reflect" if mode == "reflect"
                   else "edge")


pad = _reg("pad")(_pad_impl)
Pad = pad
register_sym_op("Pad", _pad_impl)
__all__.append("Pad")


def _space_to_depth(ins, a):
    b = a["block_size"]
    n, c, h, w = ins[0].shape
    x = ins[0].reshape(n, c, h // b, b, w // b, b)
    return x.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * b * b, h // b,
                                                 w // b)


def _depth_to_space(ins, a):
    b = a["block_size"]
    n, c, h, w = ins[0].shape
    x = ins[0].reshape(n, b, b, c // (b * b), h, w)
    return x.transpose(0, 3, 4, 1, 5, 2).reshape(n, c // (b * b), h * b,
                                                 w * b)


space_to_depth = _reg("space_to_depth")(_space_to_depth)
depth_to_space = _reg("depth_to_space")(_depth_to_space)


def _broadcast_axis(ins, a):
    axes = a["axis"]
    sizes = a["size"]
    if isinstance(axes, int):
        axes, sizes = [axes], [sizes]
    shape = list(ins[0].shape)
    for ax, sz in zip(axes, sizes):
        shape[ax] = sz
    return jnp.broadcast_to(ins[0], tuple(shape))


broadcast_axis = _reg("broadcast_axis")(_broadcast_axis)
broadcast_like = _reg("broadcast_like")(
    lambda ins, a: jnp.broadcast_to(ins[0], ins[1].shape))

# -- indexing / ordering ----------------------------------------------------
gather_nd = _reg("gather_nd")(
    lambda ins, a: ins[0][tuple(ins[1].astype(jnp.int32))])
batch_take = _reg("batch_take")(
    lambda ins, a: jnp.take_along_axis(
        ins[0], ins[1].astype(jnp.int32)[:, None], axis=1)[:, 0])
pick = _reg("pick")(
    lambda ins, a: _nn.pick(ins[0], ins[1], axis=a.get("axis", -1),
                            keepdims=a.get("keepdims", False)))
sort = _reg("sort")(
    lambda ins, a: jnp.sort(ins[0], axis=a.get("axis", -1))
    if not a.get("is_ascend") in (False, 0)
    else -jnp.sort(-ins[0], axis=a.get("axis", -1)))
argsort = _reg("argsort")(
    lambda ins, a: (jnp.argsort(ins[0], axis=a.get("axis", -1))
                    if a.get("is_ascend", True) not in (False, 0)
                    else jnp.argsort(-ins[0], axis=a.get("axis", -1)))
    .astype(a.get("dtype", jnp.float32)))


def _topk_impl(ins, a):
    return _nn.topk(ins[0], k=a.get("k", 1), axis=a.get("axis", -1),
                    ret_typ=a.get("ret_typ", "indices"),
                    is_ascend=a.get("is_ascend", False),
                    dtype=a.get("dtype", "float32"))


register_sym_op("topk", _topk_impl)


def topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False,
         name=None):
    nout = 2 if ret_typ == "both" else 1
    return Symbol.create("topk", data, name=name, nout=nout, k=k, axis=axis,
                         ret_typ=ret_typ, is_ascend=is_ascend)


__all__ += ["topk"]

take_axis = None  # (take lives in op.py)

# -- sequence ops -----------------------------------------------------------
SequenceMask = _reg("SequenceMask")(
    lambda ins, a: _nn.sequence_mask(
        ins[0], ins[1] if len(ins) > 1 else None,
        use_sequence_length=a.get("use_sequence_length", False),
        value=a.get("value", 0.0), axis=a.get("axis", 0)))
SequenceLast = _reg("SequenceLast")(
    lambda ins, a: _nn.sequence_last(
        ins[0], ins[1] if len(ins) > 1 else None,
        use_sequence_length=a.get("use_sequence_length", False),
        axis=a.get("axis", 0)))
SequenceReverse = _reg("SequenceReverse")(
    lambda ins, a: _nn.sequence_reverse(
        ins[0], ins[1] if len(ins) > 1 else None,
        use_sequence_length=a.get("use_sequence_length", False)))

# -- NN tail ----------------------------------------------------------------
softmin = _reg("softmin")(
    lambda ins, a: _nn.softmin(ins[0], axis=a.get("axis", -1)))
masked_softmax = _reg("masked_softmax")(
    lambda ins, a: jnp.where(
        ins[1].astype(bool),
        _nn.softmax(jnp.where(ins[1].astype(bool), ins[0], -1e30) /
                    a.get("temperature", 1.0), axis=a.get("axis", -1)),
        0.0))
GroupNorm = _reg("GroupNorm")(
    lambda ins, a: _nn.group_norm(ins[0], ins[1], ins[2],
                                  num_groups=a.get("num_groups", 1),
                                  eps=a.get("eps", 1e-5)))
InstanceNorm = _reg("InstanceNorm")(
    lambda ins, a: _nn.instance_norm(ins[0], ins[1], ins[2],
                                     eps=a.get("eps", 1e-3)))
RMSNorm = _reg("RMSNorm")(
    lambda ins, a: _nn.rms_norm(ins[0], ins[1], axis=a.get("axis", -1),
                                eps=a.get("eps", 1e-6)))
L2Normalization = _reg("L2Normalization")(
    lambda ins, a: _nn.l2_normalization(ins[0], mode=a.get("mode", "instance"),
                                        eps=a.get("eps", 1e-10)))
LRN = _reg("LRN")(
    lambda ins, a: _nn.lrn(ins[0], alpha=a.get("alpha", 1e-4),
                           beta=a.get("beta", 0.75), knorm=a.get("knorm", 2),
                           nsize=a.get("nsize", 5)))
UpSampling = _reg("UpSampling")(
    lambda ins, a: _nn.upsample(ins[0], scale=a.get("scale", 2),
                                sample_type=a.get("sample_type", "nearest")))
SoftmaxActivation = _reg("SoftmaxActivation")(
    lambda ins, a: _nn.softmax(
        ins[0], axis=1 if a.get("mode") == "channel" else -1))
GELU = _reg("GELU")(lambda ins, a: _nn.activation(ins[0], "erf_gelu"))
# exact erf formulation — matches the reference GELU and the ONNX converter
softplus = _reg("softplus")(
    _f(lambda x: jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(x, 0)))
log_sigmoid = _reg("log_sigmoid")(
    _f(lambda x: -(jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(-x, 0))))
mish = _reg("mish")(
    _f(lambda x: x * jnp.tanh(
        jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(x, 0))))

# -- SliceChannel (legacy alias of split) -----------------------------------
register_sym_op(
    "SliceChannel",
    lambda ins, a: tuple(jnp.split(ins[0], a["num_outputs"],
                                   axis=a.get("axis", 1))))


def SliceChannel(data, num_outputs, axis=1, squeeze_axis=False, name=None):
    if squeeze_axis:
        raise NotImplementedError("squeeze_axis=True not supported")
    return Symbol.create("SliceChannel", data, name=name, nout=num_outputs,
                         num_outputs=num_outputs, axis=axis)


__all__.append("SliceChannel")

# -- identity / blockgrad ---------------------------------------------------
identity = _reg("identity")(lambda ins, a: ins[0])
BlockGrad = _reg("BlockGrad")(lambda ins, a: lax.stop_gradient(ins[0]))
stop_gradient = BlockGrad
__all__.append("stop_gradient")
make_loss = _reg("make_loss")(lambda ins, a: ins[0])

# -- arange_like (positions for attention) ----------------------------------


def _arange_like_impl(ins, a):
    """Matches the imperative op (ops/tensor.py arange_like): axis=None
    fills data.shape; `repeat` emits each value repeat times."""
    axis = a.get("axis")
    repeat = a.get("repeat", 1)
    step = a.get("step", 1.0)
    start = a.get("start", 0.0)
    data = ins[0]
    n = data.shape[axis] if axis is not None else data.size
    count = -(-n // repeat) if repeat > 1 else n
    out = jnp.arange(count, dtype=jnp.float32) * step + start
    if repeat > 1:
        out = jnp.repeat(out, repeat)[:n]
    if axis is None:
        return out.reshape(data.shape)
    return out


arange_like = _reg("arange_like")(_arange_like_impl)


# -- linalg family (la_op.cc parity at the symbol level) --------------------
# lowerings reuse the registry's pure implementations so symbolic ==
# imperative for the whole linalg_* corpus
# multi-output members (reference la_op.cc: gelqf -> Q,L; syevd -> U,L;
# plus the np-backed additions)
_LINALG_NOUT = {"linalg_gelqf": 2, "linalg_syevd": 2, "linalg_svd": 3,
                "linalg_qr": 2, "linalg_slogdet": 2, "linalg_eig": 2,
                "linalg_eigh": 2, "linalg_lstsq": 4}


def _register_linalg():
    import inspect

    from ..ops.registry import _OPS

    added = []
    for opname, fn in sorted(_OPS.items()):
        if not opname.startswith("linalg_"):
            continue
        try:
            params = set(inspect.signature(fn).parameters)
        except (TypeError, ValueError):
            params = None
        nout = _LINALG_NOUT.get(opname, 1)

        def lower(ins, a, _f=fn, _params=params):
            # keep only kwargs the op accepts — AttrScope can inject
            # bookkeeping attrs (ctx_group...) that must not reach the fn
            kw = {k: v for k, v in a.items()
                  if _params is None or k in _params}
            return _f(*ins, **kw)

        register_sym_op(opname, lower)

        def wrapper(*inputs, name=None, _op=opname, _n=nout,  # noqa: A002
                    **attrs):
            return Symbol.create(_op, *inputs, name=name, nout=_n, **attrs)

        wrapper.__name__ = opname
        globals()[opname] = wrapper
        __all__.append(opname)
        added.append(opname)
    return added


_register_linalg()
