"""mx.symbol — legacy lazy-graph API (reference: python/mxnet/symbol/
symbol.py:54 `Symbol`, ~15.8k LoC).

TPU re-design: a Symbol is a lightweight DAG node over the same pure-jax
op implementations the imperative frontends use (mxnet_tpu/ops). There is
no separate graph engine — `bind` lowers the DAG to one pure function and
compiles it with jax.jit (the GraphExecutor ≙ XLA program), `infer_shape`
is jax.eval_shape on that function (reference: infer_graph_attr_pass.cc),
and Executor.backward is jax.vjp. tojson/save/load round-trip the DAG for
model export (reference: model-symbol.json).
"""
from .symbol import (Executor, Group, Symbol, Variable, fromjson, load,
                     load_json, var, zeros, ones)
from . import op  # registers the op table; also exposes sym.op.* wrappers
from .op import *  # noqa: F401,F403
from . import linalg  # noqa: F401
from . import random  # noqa: F401
from . import op_extended  # math tail, indexing, sequence, norms
from .op_extended import *  # noqa: F401,F403
from . import register as _register  # generated builders for the full
#                                      registry (reference: symbol/register.py)
from . import contrib  # noqa: F401  (symbolic control flow + contrib ops)
from . import sparse  # noqa: F401
from . import image  # noqa: F401
from . import _internal  # noqa: F401

# numpy-flavored submodules (reference: symbol/__init__.py imports
# .numpy / .numpy_extension; shared frontend here — see ndarray/__init__)
from .. import numpy  # noqa: F401
from .. import numpy as np  # noqa: F401
from .. import numpy_extension  # noqa: F401
from .. import numpy_extension as npx  # noqa: F401

__all__ = (["Symbol", "Variable", "Group", "Executor", "var", "load",
            "load_json", "fromjson", "zeros", "ones"]
           + op.__all__ + op_extended.__all__)


def __getattr__(name):
    """Resolve any registered op as mx.sym.<name> (curated wrappers above
    take normal attribute priority; this fallback covers the rest of the
    700+-op registry, like the reference's generated namespace)."""
    builder = _register.get_builder(name)
    if builder is not None:
        return builder
    raise AttributeError(f"module 'mxnet_tpu.symbol' has no attribute "
                         f"{name!r}")
