"""Symbolic model builders (reference: the mx.sym model definitions of
example/image-classification/symbols/*.py and the gluon model_zoo
architectures re-expressed as symbol graphs).

These produce pure `mx.symbol` DAGs — the deployment/ONNX-export form.
Each builder returns (sym, param_shapes): `param_shapes` maps every
non-data argument to its shape so callers can materialize random or
loaded weights for `bind`/`export_model`.
"""
from __future__ import annotations

import math

from . import op as _op
from . import op_extended as _ext
from .symbol import Symbol, var

__all__ = ["lenet_symbol", "mlp_symbol", "resnet_symbol", "bert_symbol",
           "get_symbol"]


class _P:
    """Collects parameter variables + shapes as they are declared."""

    def __init__(self):
        self.shapes = {}

    def var(self, name, shape):
        self.shapes[name] = tuple(shape)
        return var(name)


def mlp_symbol(num_classes=10, in_units=784, hidden=(128, 64)):
    """Feed-forward classifier (reference: symbols/mlp.py shape)."""
    p = _P()
    x = var("data")
    h = x
    prev = in_units
    for i, units in enumerate(hidden):
        w = p.var(f"fc{i}_weight", (units, prev))
        b = p.var(f"fc{i}_bias", (units,))
        h = _op.Activation(_op.FullyConnected(h, w, b, num_hidden=units),
                           "relu")
        prev = units
    w = p.var("out_weight", (num_classes, prev))
    b = p.var("out_bias", (num_classes,))
    out = _op.softmax(_op.FullyConnected(h, w, b, num_hidden=num_classes))
    return out, p.shapes


def lenet_symbol(num_classes=10):
    """LeNet-5 graph (reference: symbols/lenet.py shape)."""
    p = _P()
    x = var("data")  # (N, 1, 28, 28)
    c1 = _op.Convolution(x, p.var("conv0_weight", (6, 1, 5, 5)),
                         p.var("conv0_bias", (6,)), kernel=(5, 5),
                         num_filter=6, pad=(2, 2))
    a1 = _op.Activation(c1, "tanh")
    s1 = _op.Pooling(a1, kernel=(2, 2), pool_type="avg", stride=(2, 2))
    c2 = _op.Convolution(s1, p.var("conv1_weight", (16, 6, 5, 5)),
                         p.var("conv1_bias", (16,)), kernel=(5, 5),
                         num_filter=16)
    a2 = _op.Activation(c2, "tanh")
    s2 = _op.Pooling(a2, kernel=(2, 2), pool_type="avg", stride=(2, 2))
    f = _op.Flatten(s2)
    h = _op.Activation(
        _op.FullyConnected(f, p.var("fc0_weight", (120, 400)),
                           p.var("fc0_bias", (120,)), num_hidden=120),
        "tanh")
    h = _op.Activation(
        _op.FullyConnected(h, p.var("fc1_weight", (84, 120)),
                           p.var("fc1_bias", (84,)), num_hidden=84),
        "tanh")
    out = _op.softmax(
        _op.FullyConnected(h, p.var("fc2_weight", (num_classes, 84)),
                           p.var("fc2_bias", (num_classes,)),
                           num_hidden=num_classes))
    return out, p.shapes


def _conv_bn_relu(p, x, name, c_in, c_out, kernel, stride, pad, relu=True):
    w = p.var(f"{name}_weight", (c_out, c_in) + kernel)
    y = _op.Convolution(x, w, None, kernel=kernel, num_filter=c_out,
                        stride=stride, pad=pad, no_bias=True, name=name)
    g = p.var(f"{name}_bn_gamma", (c_out,))
    b = p.var(f"{name}_bn_beta", (c_out,))
    mm = p.var(f"{name}_bn_mean", (c_out,))
    mv = p.var(f"{name}_bn_var", (c_out,))
    y = _op.BatchNorm(y, g, b, mm, mv, name=f"{name}_bn")
    if relu:
        y = _op.Activation(y, "relu", name=f"{name}_relu")
    return y


def resnet_symbol(num_layers=18, num_classes=1000):
    """ResNet-v1 basic/bottleneck graph (reference:
    symbols/resnet.py / gluon model_zoo resnet architecture)."""
    specs = {18: ([2, 2, 2, 2], [64, 64, 128, 256, 512], "basic"),
             34: ([3, 4, 6, 3], [64, 64, 128, 256, 512], "basic"),
             50: ([3, 4, 6, 3], [64, 256, 512, 1024, 2048], "bottleneck")}
    layers, channels, kind = specs[num_layers]
    p = _P()
    x = var("data")  # (N, 3, H, W)
    y = _conv_bn_relu(p, x, "stem", 3, channels[0], (7, 7), (2, 2), (3, 3))
    y = _op.Pooling(y, kernel=(3, 3), pool_type="max", stride=(2, 2),
                    pad=(1, 1))
    c_in = channels[0]
    for stage, (n, c_out) in enumerate(zip(layers, channels[1:])):
        stride = (1, 1) if stage == 0 else (2, 2)
        for blk in range(n):
            nm = f"s{stage}b{blk}"
            s = stride if blk == 0 else (1, 1)
            if kind == "basic":
                body = _conv_bn_relu(p, y, f"{nm}_c0", c_in, c_out, (3, 3),
                                     s, (1, 1))
                body = _conv_bn_relu(p, body, f"{nm}_c1", c_out, c_out,
                                     (3, 3), (1, 1), (1, 1), relu=False)
            else:
                mid = c_out // 4
                body = _conv_bn_relu(p, y, f"{nm}_c0", c_in, mid, (1, 1),
                                     s, (0, 0))
                body = _conv_bn_relu(p, body, f"{nm}_c1", mid, mid, (3, 3),
                                     (1, 1), (1, 1))
                body = _conv_bn_relu(p, body, f"{nm}_c2", mid, c_out,
                                     (1, 1), (1, 1), (0, 0), relu=False)
            if blk == 0 and (c_in != c_out or s != (1, 1)):
                sc = _conv_bn_relu(p, y, f"{nm}_sc", c_in, c_out, (1, 1),
                                   s, (0, 0), relu=False)
            else:
                sc = y
            y = _op.Activation(body + sc, "relu", name=f"{nm}_out")
            c_in = c_out
    y = _op.Pooling(y, global_pool=True, pool_type="avg", kernel=(1, 1))
    y = _op.Flatten(y)
    out = _op.softmax(
        _op.FullyConnected(y, p.var("fc_weight", (num_classes, c_in)),
                           p.var("fc_bias", (num_classes,)),
                           num_hidden=num_classes))
    return out, p.shapes


def bert_symbol(num_layers=2, units=64, num_heads=2, hidden_size=128,
                vocab_size=1000, max_length=64, seq_len=16):
    """BERT encoder + QA span head as a symbol graph (architecture:
    gluon/model_zoo/bert.py; reference ONNX-export target per the
    mx2onnx BERT coverage in _op_translations).

    Returns logits (N, seq_len, 2) — start/end span scores.
    """
    assert units % num_heads == 0
    d = units // num_heads
    p = _P()
    tokens = var("data0")     # (N, S) token ids
    segments = var("data1")   # (N, S) segment ids

    word_emb = _ext.cast(
        _op.Embedding(tokens, p.var("word_embed_weight",
                                    (vocab_size, units))), dtype="float32")
    seg_emb = _ext.cast(
        _op.Embedding(segments, p.var("token_type_embed_weight",
                                      (2, units))), dtype="float32")
    pos_full = p.var("position_weight", (max_length, units))
    pos_emb = _op.slice(pos_full, begin=(0, 0), end=(seq_len, units))
    x = _op.broadcast_add(word_emb + seg_emb,
                          _op.expand_dims(pos_emb, axis=0))
    x = _op.LayerNorm(x, p.var("embed_ln_gamma", (units,)),
                      p.var("embed_ln_beta", (units,)))

    for i in range(num_layers):
        nm = f"layer{i}"
        qkv_w = p.var(f"{nm}_qkv_weight", (3 * units, units))
        qkv_b = p.var(f"{nm}_qkv_bias", (3 * units,))
        qkv = _op.FullyConnected(x, qkv_w, qkv_b, num_hidden=3 * units,
                                 flatten=False)          # (N, S, 3U)
        qkv = _op.reshape(qkv, shape=(-1, seq_len, 3, num_heads, d))
        qkv = _op.transpose(qkv, axes=(2, 0, 3, 1, 4))   # (3, N, H, S, d)
        q = _op.reshape(_op.slice_axis(qkv, axis=0, begin=0, end=1),
                        shape=(-1, seq_len, d))          # (N*H, S, d)
        k = _op.reshape(_op.slice_axis(qkv, axis=0, begin=1, end=2),
                        shape=(-1, seq_len, d))
        v = _op.reshape(_op.slice_axis(qkv, axis=0, begin=2, end=3),
                        shape=(-1, seq_len, d))
        scores = _op.batch_dot(q, _op.transpose(k, axes=(0, 2, 1)))
        att = _op.softmax(scores / math.sqrt(d))
        ctxv = _op.batch_dot(att, v)                     # (N*H, S, d)
        ctxv = _op.reshape(ctxv, shape=(-1, num_heads, seq_len, d))
        ctxv = _op.transpose(ctxv, axes=(0, 2, 1, 3))
        ctxv = _op.reshape(ctxv, shape=(-1, seq_len, units))
        proj = _op.FullyConnected(
            ctxv, p.var(f"{nm}_proj_weight", (units, units)),
            p.var(f"{nm}_proj_bias", (units,)), num_hidden=units,
            flatten=False)
        x = _op.LayerNorm(x + proj, p.var(f"{nm}_ln0_gamma", (units,)),
                          p.var(f"{nm}_ln0_beta", (units,)))
        ffn = _ext.GELU(_op.FullyConnected(
            x, p.var(f"{nm}_ffn0_weight", (hidden_size, units)),
            p.var(f"{nm}_ffn0_bias", (hidden_size,)),
            num_hidden=hidden_size, flatten=False))
        ffn = _op.FullyConnected(
            ffn, p.var(f"{nm}_ffn1_weight", (units, hidden_size)),
            p.var(f"{nm}_ffn1_bias", (units,)), num_hidden=units,
            flatten=False)
        x = _op.LayerNorm(x + ffn, p.var(f"{nm}_ln1_gamma", (units,)),
                          p.var(f"{nm}_ln1_beta", (units,)))

    logits = _op.FullyConnected(
        x, p.var("qa_weight", (2, units)), p.var("qa_bias", (2,)),
        num_hidden=2, flatten=False)                     # (N, S, 2)
    return logits, p.shapes


_BUILDERS = {"mlp": mlp_symbol, "lenet": lenet_symbol,
             "resnet": resnet_symbol, "bert": bert_symbol}


def get_symbol(name, **kwargs):
    """Build a named symbolic model: mlp | lenet | resnet | bert."""
    if name not in _BUILDERS:
        raise ValueError(f"unknown symbolic model {name!r}; "
                         f"choose from {sorted(_BUILDERS)}")
    return _BUILDERS[name](**kwargs)
