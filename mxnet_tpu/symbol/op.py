"""Symbol operator wrappers (reference: generated mx.sym.* from the op
registry — symbol/register.py). Each op lowers to the same pure-jax
implementations the imperative frontends use (mxnet_tpu/ops/nn.py, jnp),
so symbolic and imperative results agree by construction (the
check_consistency property the reference tested for).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops import nn as _nn
from .symbol import Symbol, register_sym_op

__all__ = [
    "FullyConnected", "Convolution", "Deconvolution", "Activation",
    "Pooling", "BatchNorm", "LayerNorm", "Dropout", "Flatten", "Concat",
    "SoftmaxOutput", "softmax", "log_softmax", "exp", "log", "sqrt",
    "square", "tanh", "sigmoid", "relu", "abs", "negative", "dot",
    "batch_dot", "sum", "mean", "max", "min", "prod", "argmax", "argmin",
    "transpose", "reshape", "expand_dims", "squeeze", "slice",
    "slice_axis", "split", "stack", "where", "maximum", "minimum",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_to", "zeros_like", "ones_like", "clip", "norm", "power",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "LeakyReLU", "Embedding", "take", "one_hot", "swapaxes",
]


def _reg(name, nin=None, nout=1):
    """Register table entry + return a Symbol-building wrapper."""
    def deco(fn):
        register_sym_op(name, fn)

        def wrapper(*inputs, name=None, **attrs):
            return Symbol.create(name_, *inputs, name=name, nout=nout,
                                 **attrs)

        name_ = name
        wrapper.__name__ = name
        return wrapper

    return deco


# -- elementwise ------------------------------------------------------------
elemwise_add = _reg("elemwise_add")(lambda ins, a: ins[0] + ins[1])
elemwise_sub = _reg("elemwise_sub")(lambda ins, a: ins[0] - ins[1])
elemwise_mul = _reg("elemwise_mul")(lambda ins, a: ins[0] * ins[1])
elemwise_div = _reg("elemwise_div")(lambda ins, a: ins[0] / ins[1])
broadcast_add = _reg("broadcast_add")(lambda ins, a: ins[0] + ins[1])
broadcast_sub = _reg("broadcast_sub")(lambda ins, a: ins[0] - ins[1])
broadcast_mul = _reg("broadcast_mul")(lambda ins, a: ins[0] * ins[1])
broadcast_div = _reg("broadcast_div")(lambda ins, a: ins[0] / ins[1])
power = _reg("power")(lambda ins, a: ins[0] ** ins[1])
negative = _reg("negative")(lambda ins, a: -ins[0])
exp = _reg("exp")(lambda ins, a: jnp.exp(ins[0]))
log = _reg("log")(lambda ins, a: jnp.log(ins[0]))
sqrt = _reg("sqrt")(lambda ins, a: jnp.sqrt(ins[0]))
square = _reg("square")(lambda ins, a: jnp.square(ins[0]))
tanh = _reg("tanh")(lambda ins, a: jnp.tanh(ins[0]))
abs = _reg("abs")(lambda ins, a: jnp.abs(ins[0]))  # noqa: A001
sigmoid = _reg("sigmoid")(
    lambda ins, a: _nn.activation(ins[0], "sigmoid"))
relu = _reg("relu")(lambda ins, a: _nn.activation(ins[0], "relu"))
maximum = _reg("maximum")(lambda ins, a: jnp.maximum(ins[0], ins[1]))
minimum = _reg("minimum")(lambda ins, a: jnp.minimum(ins[0], ins[1]))
where = _reg("where")(
    lambda ins, a: jnp.where(ins[0].astype(bool), ins[1], ins[2]))
clip = _reg("clip")(
    lambda ins, a: jnp.clip(ins[0], a.get("a_min"), a.get("a_max")))
zeros_like = _reg("zeros_like")(lambda ins, a: jnp.zeros_like(ins[0]))
ones_like = _reg("ones_like")(lambda ins, a: jnp.ones_like(ins[0]))

# -- reduce -----------------------------------------------------------------


def _axis(a):
    ax = a.get("axis")
    if isinstance(ax, list):
        ax = tuple(ax)
    return ax


sum = _reg("sum")(  # noqa: A001
    lambda ins, a: jnp.sum(ins[0], axis=_axis(a),
                           keepdims=a.get("keepdims", False)))
mean = _reg("mean")(
    lambda ins, a: jnp.mean(ins[0], axis=_axis(a),
                            keepdims=a.get("keepdims", False)))
max = _reg("max")(  # noqa: A001
    lambda ins, a: jnp.max(ins[0], axis=_axis(a),
                           keepdims=a.get("keepdims", False)))
min = _reg("min")(  # noqa: A001
    lambda ins, a: jnp.min(ins[0], axis=_axis(a),
                           keepdims=a.get("keepdims", False)))
prod = _reg("prod")(
    lambda ins, a: jnp.prod(ins[0], axis=_axis(a),
                            keepdims=a.get("keepdims", False)))
argmax = _reg("argmax")(
    lambda ins, a: jnp.argmax(ins[0], axis=a.get("axis")).astype(
        jnp.float32))
argmin = _reg("argmin")(
    lambda ins, a: jnp.argmin(ins[0], axis=a.get("axis")).astype(
        jnp.float32))
norm = _reg("norm")(
    lambda ins, a: jnp.linalg.norm(ins[0], ord=a.get("ord", 2),
                                   axis=_axis(a),
                                   keepdims=a.get("keepdims", False)))

# -- shape ------------------------------------------------------------------
transpose = _reg("transpose")(
    lambda ins, a: jnp.transpose(ins[0], a.get("axes")))
reshape = _reg("reshape")(
    lambda ins, a: jnp.reshape(ins[0], tuple(a["shape"])))
expand_dims = _reg("expand_dims")(
    lambda ins, a: jnp.expand_dims(ins[0], a["axis"]))
squeeze = _reg("squeeze")(
    lambda ins, a: jnp.squeeze(ins[0], _axis(a)))
swapaxes = _reg("swapaxes")(
    lambda ins, a: jnp.swapaxes(ins[0], a["dim1"], a["dim2"]))
broadcast_to = _reg("broadcast_to")(
    lambda ins, a: jnp.broadcast_to(ins[0], tuple(a["shape"])))
Flatten = _reg("Flatten")(
    lambda ins, a: jnp.reshape(ins[0], (ins[0].shape[0], -1)))


def _slice_impl(ins, a):
    import builtins

    begin, end = a["begin"], a["end"]
    step = a.get("step") or [None] * len(begin)
    return ins[0][tuple(builtins.slice(b, e, s)
                        for b, e, s in zip(begin, end, step))]


slice = _reg("slice")(_slice_impl)  # noqa: A001


def _slice_axis_impl(ins, a):
    import builtins

    sl = [builtins.slice(None)] * ins[0].ndim
    sl[a["axis"]] = builtins.slice(a["begin"], a["end"])
    return ins[0][tuple(sl)]


slice_axis = _reg("slice_axis")(_slice_axis_impl)

register_sym_op("split",
                lambda ins, a: tuple(jnp.split(ins[0], a["num_outputs"],
                                               axis=a.get("axis", 1))))


def split(data, num_outputs, axis=1, name=None, **kw):  # noqa: ARG001
    """Multi-output split — the Symbol carries nout=num_outputs so
    indexing/list_outputs see every piece."""
    return Symbol.create("split", data, name=name, nout=num_outputs,
                         num_outputs=num_outputs, axis=axis)


def Concat(*inputs, dim=1, name=None, **kw):  # noqa: ARG001
    return Symbol.create("Concat", *inputs, name=name, dim=dim)


register_sym_op("Concat",
                lambda ins, a: jnp.concatenate(ins, axis=a.get("dim", 1)))


def stack(*inputs, axis=0, name=None):
    return Symbol.create("stack", *inputs, name=name, axis=axis)


register_sym_op("stack",
                lambda ins, a: jnp.stack(ins, axis=a.get("axis", 0)))

# -- linalg -----------------------------------------------------------------
dot = _reg("dot")(lambda ins, a: jnp.dot(ins[0], ins[1]))
batch_dot = _reg("batch_dot")(
    lambda ins, a: jnp.einsum("bij,bjk->bik", ins[0], ins[1]))
take = _reg("take")(
    lambda ins, a: jnp.take(ins[0], ins[1].astype(jnp.int32),
                            axis=a.get("axis", 0)))


def Embedding(data, weight=None, input_dim=None, output_dim=None,
              name=None, attr=None, **kw):
    attr = _annot_kwargs(attr, kw)
    name = _resolve_name(name, "embedding")
    if weight is None:
        weight = _auto_param(name, "weight", attr)
    return Symbol.create("Embedding", data, weight, name=name, attr=attr,
                         input_dim=input_dim, output_dim=output_dim)


register_sym_op(
    "Embedding",
    lambda ins, a: _nn.embedding(ins[0].astype(jnp.int32), ins[1]))
one_hot = _reg("one_hot")(
    lambda ins, a: _nn.one_hot(ins[0].astype(jnp.int32), a["depth"]))

# -- NN layers --------------------------------------------------------------
softmax = _reg("softmax")(
    lambda ins, a: _nn.softmax(ins[0], axis=a.get("axis", -1)))
log_softmax = _reg("log_softmax")(
    lambda ins, a: _nn.log_softmax(ins[0], axis=a.get("axis", -1)))



def _resolve_name(name, hint):
    from .. import name as _name_mod

    return _name_mod.current().get(name, hint)


# GPU-only knobs reference call sites pass freely; meaningless on TPU
_IGNORED_KWARGS = frozenset({"cudnn_off", "cudnn_tune", "workspace"})


def _annot_kwargs(attr, kw):
    """Move lr_mult-style annotation kwargs from a builder's **kw into
    the attr dict (the reference accepts them on any symbol call), and
    warn on anything else unrecognized — silently swallowing a
    misspelled kwarg (num_hiden=...) hides the bug until bind time."""
    import warnings

    from .symbol import Symbol

    attr = dict(attr or {})
    for k in [k for k in kw if k in Symbol._MIRROR_KEYS]:
        attr[k] = kw.pop(k)
    unknown = [k for k in kw if k not in _IGNORED_KWARGS]
    if unknown:
        warnings.warn(f"ignored symbol kwargs {unknown}", stacklevel=3)
    return attr


def _auto_param(final_name, slot, attr):
    """Reference nnvm composition: an omitted parameter input becomes a
    variable named {opname}_{slot}, inheriting the op's __dunder__
    annotation attrs (test_attr.py:72 conv_weight['__mood__'])."""
    from .symbol import Symbol, var

    v = var(f"{final_name}_{slot}")
    dunder = {k: val for k, val in Symbol._normalize_user_attrs(
        dict(attr or {})).items() if k.startswith("__")}
    v._uattrs.update(dunder)
    return v

def FullyConnected(data, weight=None, bias=None, num_hidden=None,
                   no_bias=False, flatten=True, name=None, attr=None,
                   **kw):
    attr = _annot_kwargs(attr, kw)
    name = _resolve_name(name, "fullyconnected")
    if weight is None:
        weight = _auto_param(name, "weight", attr)
    if bias is None and not no_bias:
        bias = _auto_param(name, "bias", attr)
    ins = (data, weight) if no_bias else (data, weight, bias)
    return Symbol.create("FullyConnected", *ins, name=name, attr=attr,
                         no_bias=bool(no_bias),
                         num_hidden=num_hidden, flatten=flatten)


register_sym_op(
    "FullyConnected",
    lambda ins, a: _nn.dense(ins[0], ins[1],
                             None if a.get("no_bias") else ins[2],
                             flatten=a.get("flatten", True)))


def Convolution(data, weight=None, bias=None, kernel=None, num_filter=None,
                stride=None, pad=None, dilate=None, num_group=1,
                no_bias=False, name=None, attr=None, **kw):  # noqa: ARG001
    attr = _annot_kwargs(attr, kw)
    name = _resolve_name(name, "convolution")
    if weight is None:
        weight = _auto_param(name, "weight", attr)
    if bias is None and not no_bias:
        bias = _auto_param(name, "bias", attr)
    ins = (data, weight) if no_bias else (data, weight, bias)
    return Symbol.create("Convolution", *ins, name=name, attr=attr,
                         no_bias=bool(no_bias),
                         kernel=kernel, num_filter=num_filter,
                         stride=stride, pad=pad, dilate=dilate,
                         num_group=num_group)


register_sym_op(
    "Convolution",
    lambda ins, a: _nn.conv(ins[0], ins[1],
                            None if a.get("no_bias") else ins[2],
                            stride=a.get("stride"), pad=a.get("pad"),
                            dilate=a.get("dilate"),
                            groups=a.get("num_group", 1)))


def Deconvolution(data, weight=None, bias=None, no_bias=False, stride=None,
                  pad=None, name=None, attr=None, **kw):  # noqa: ARG001
    # op params arrive through **kw here — pull them out BEFORE the
    # annotation sweep or every call warns they were "ignored"
    kernel = kw.pop("kernel", None)
    num_filter = kw.pop("num_filter", None)
    num_group = kw.pop("num_group", 1)
    attr = _annot_kwargs(attr, kw)
    name = _resolve_name(name, "deconvolution")
    if weight is None:
        weight = _auto_param(name, "weight", attr)
    if bias is None and not no_bias:
        bias = _auto_param(name, "bias", attr)
    ins = (data, weight) if no_bias else (data, weight, bias)
    return Symbol.create("Deconvolution", *ins, name=name, attr=attr,
                         no_bias=bool(no_bias),
                         kernel=kernel, num_filter=num_filter,
                         num_group=num_group,
                         stride=stride, pad=pad)


register_sym_op(
    "Deconvolution",
    lambda ins, a: _nn.conv_transpose(
        ins[0], ins[1], None if a.get("no_bias") else ins[2],
        stride=a.get("stride"), pad=a.get("pad")))


def Activation(data, act_type="relu", name=None):
    return Symbol.create("Activation", data, name=name, act_type=act_type)


register_sym_op("Activation",
                lambda ins, a: _nn.activation(ins[0],
                                              a.get("act_type", "relu")))


def LeakyReLU(data, act_type="leaky", slope=0.25, name=None):
    return Symbol.create("LeakyReLU", data, name=name, act_type=act_type,
                         slope=slope)


register_sym_op(
    "LeakyReLU",
    lambda ins, a: _nn.leaky_relu(ins[0], None,
                                  act_type=a.get("act_type", "leaky"),
                                  slope=a.get("slope", 0.25)))


def Pooling(data, kernel=(2, 2), pool_type="max", stride=None, pad=None,
            global_pool=False, name=None, **kw):  # noqa: ARG001
    return Symbol.create("Pooling", data, name=name, kernel=kernel,
                         pool_type=pool_type, stride=stride, pad=pad,
                         global_pool=global_pool)


register_sym_op(
    "Pooling",
    lambda ins, a: _nn.pool(ins[0], a.get("kernel", (2, 2)),
                            pool_type=a.get("pool_type", "max"),
                            stride=a.get("stride"), pad=a.get("pad"),
                            global_pool=a.get("global_pool", False)))


def BatchNorm(data, gamma=None, beta=None, moving_mean=None,
              moving_var=None, eps=1e-5, momentum=0.9, fix_gamma=False,
              use_global_stats=True, name=None, attr=None, **kw):
    """Inference-mode BN (symbolic graphs are deployment artifacts; train
    BN lives in gluon.nn.BatchNorm)."""
    attr = _annot_kwargs(attr, kw)
    name = _resolve_name(name, "batchnorm")
    gamma = gamma if gamma is not None else _auto_param(name, "gamma", attr)
    beta = beta if beta is not None else _auto_param(name, "beta", attr)
    moving_mean = moving_mean if moving_mean is not None \
        else _auto_param(name, "moving_mean", attr)
    moving_var = moving_var if moving_var is not None \
        else _auto_param(name, "moving_var", attr)
    return Symbol.create("BatchNorm", data, gamma, beta, moving_mean,
                         moving_var, name=name, attr=attr, eps=eps)


register_sym_op(
    "BatchNorm",
    lambda ins, a: _nn.batch_norm(ins[0], ins[1], ins[2], ins[3], ins[4],
                                  eps=a.get("eps", 1e-5),
                                  use_global_stats=True)[0])


def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5, name=None):
    return Symbol.create("LayerNorm", data, gamma, beta, name=name,
                         axis=axis, eps=eps)


register_sym_op(
    "LayerNorm",
    lambda ins, a: _nn.layer_norm(ins[0], ins[1], ins[2],
                                  axis=a.get("axis", -1),
                                  eps=a.get("eps", 1e-5)))


def Dropout(data, p=0.5, name=None, **kw):  # noqa: ARG001
    """Identity in symbolic graphs (deployment = inference; reference
    Dropout also no-ops outside training mode)."""
    return Symbol.create("Dropout", data, name=name, p=p)


register_sym_op("Dropout", lambda ins, a: ins[0])


def SoftmaxOutput(data, label=None, name=None, **kw):  # noqa: ARG001
    """Softmax for deployment (the loss part of the reference op applies
    only in training graphs)."""
    return Symbol.create("softmax", data, name=name or "softmax", axis=-1)
