"""`mx.sym.sparse` namespace (reference: mxnet/symbol/sparse.py — the
generated sparse op family, `gen_sparse`).

TPU re-design note: symbolic graphs lower to dense XLA programs (sparse
storage is an imperative-frontend concept here — see docs/sparse.md), so
the sparse symbol ops are the same registry builders under the
reference's sparse spellings; `cast_storage`/`retain` keep their
reference call signatures and dense-equivalent numerics.
"""
from . import register as _register

__all__ = ["dot", "retain", "cast_storage", "zeros_like", "elemwise_add",
           "elemwise_sub", "elemwise_mul", "add_n", "where", "LinearRegressionOutput"]

_ALIAS = {"retain": "_sparse_retain"}


def __getattr__(name):
    builder = _register.get_builder(_ALIAS.get(name, name))
    if builder is not None:
        return builder
    raise AttributeError(f"mx.sym.sparse has no op {name!r}")


def __dir__():
    return sorted(__all__)
