"""Symbol core: DAG nodes, graph lowering, executor, (de)serialization.

Reference: python/mxnet/symbol/symbol.py (Symbol:54, bind/simple_bind,
list_arguments, infer_shape, tojson) + src/nnvm graph passes. Here the
graph IS a pure jax function; every pass the reference hand-wrote
(shape inference, memory planning, fusion, gradient) is delegated to
jax.eval_shape / XLA / jax.vjp.
"""
from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as _np

_OP_TABLE = {}  # op name -> fn(list_of_arrays, attrs) -> array or tuple


def register_sym_op(name, fn):
    _OP_TABLE[name] = fn
    return fn


def _op_fn(name):
    """Op lowering by name; resyncs the generated adapters if the registry
    grew since import (deserialized graphs may reference late-registered
    ops)."""
    if name not in _OP_TABLE:
        from . import register as _register

        _register._generate()
    if name not in _OP_TABLE:
        raise ValueError(f"unknown symbol op {name!r} (not in the op "
                         "registry — stale or foreign graph json?)")
    return _OP_TABLE[name]


class Symbol:
    """A node in the lazy graph. Immutable; identity = python object."""

    __slots__ = ("_op", "_name", "_inputs", "_attrs", "_nout",
                 "_out_index", "_uattrs")

    _auto_count = {}

    # Variable kwargs the reference mirrors into __dunder__ hidden attrs
    # (python/mxnet/symbol/symbol.py var(): lr_mult -> __lr_mult__ ...)
    # NB: no real op kwarg (dtype, axis, ...) may appear here — create()
    # pops these out of the op's attrs
    _MIRROR_KEYS = frozenset(
        {"lr_mult", "wd_mult", "force_mirroring", "profiler_scope"})

    def __init__(self, op, name, inputs, attrs=None, nout=1, out_index=None,
                 uattrs=None):
        self._op = op            # None => variable (leaf)
        self._name = name
        self._inputs = list(inputs)
        self._attrs = dict(attrs or {})
        self._nout = nout
        self._out_index = out_index  # set when slicing a multi-output node
        # annotation attrs (AttrScope / attr= / lr_mult-style kwargs) —
        # kept apart from _attrs, which doubles as the op's kwargs
        self._uattrs = dict(uattrs or {})

    @staticmethod
    def _normalize_user_attrs(d):
        out = {}
        for k, v in (d or {}).items():
            v = v if isinstance(v, str) else str(v)
            out[k] = v
            if not k.startswith("__") and k in Symbol._MIRROR_KEYS:
                out[f"__{k}__"] = v
        return out

    # -- construction helpers ---------------------------------------------
    @staticmethod
    def _auto_name(op):
        i = Symbol._auto_count.get(op, 0)
        Symbol._auto_count[op] = i + 1
        return f"{op.lower()}{i}"

    @staticmethod
    def create(op, *inputs, name=None, nout=1, **attrs):
        if op not in _OP_TABLE:
            # the registry grows as modules import (contrib, custom ops);
            # resync the generated adapters before giving up
            from . import register as _register

            _register._generate()
        if op not in _OP_TABLE:
            raise ValueError(f"unknown symbol op {op!r}")
        inputs = [s if isinstance(s, Symbol) else _const(s) for s in inputs]
        # honor the ambient NameManager/Prefix and AttrScope
        # (reference: symbol creation consults both scopes)
        from .. import attribute as _attr_mod
        from .. import name as _name_mod

        # annotation attrs (attr= dict, lr_mult-style kwargs, AttrScope)
        # ride _uattrs; _attrs stays the op's real kwargs for lowering
        user = dict(attrs.pop("attr", None) or {})
        for k in [k for k in attrs if k in Symbol._MIRROR_KEYS]:
            user[k] = attrs.pop(k)
        uattrs = _attr_mod.current().get(
            Symbol._normalize_user_attrs(user))

        final_name = _name_mod.current().get(name, op.lower())
        return Symbol(op, final_name, inputs, attrs, nout=nout,
                      uattrs=uattrs)

    # -- python operators --------------------------------------------------
    def __add__(self, o):
        return Symbol.create("elemwise_add", self, o)

    __radd__ = __add__

    def __sub__(self, o):
        return Symbol.create("elemwise_sub", self, o)

    def __rsub__(self, o):
        return Symbol.create("elemwise_sub", _const(o), self)

    def __mul__(self, o):
        return Symbol.create("elemwise_mul", self, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return Symbol.create("elemwise_div", self, o)

    def __rtruediv__(self, o):
        return Symbol.create("elemwise_div", _const(o), self)

    def __pow__(self, o):
        return Symbol.create("power", self, o)

    def __neg__(self):
        return Symbol.create("negative", self)

    def __mod__(self, o):
        return Symbol.create("broadcast_mod", self, o)

    def __rmod__(self, o):
        return Symbol.create("broadcast_mod", _const(o), self)

    def __abs__(self):
        return Symbol.create("abs", self)

    # elementwise comparisons (reference: symbol.py:333-404 — Symbol
    # identity stays object-based: __hash__ below, id()-keyed graph walks)
    def __eq__(self, o):
        if o is None:
            return False
        return Symbol.create("broadcast_equal", self, o)

    def __ne__(self, o):
        if o is None:
            return True
        return Symbol.create("broadcast_not_equal", self, o)

    def __lt__(self, o):
        return Symbol.create("broadcast_lesser", self, o)

    def __le__(self, o):
        return Symbol.create("broadcast_lesser_equal", self, o)

    def __gt__(self, o):
        return Symbol.create("broadcast_greater", self, o)

    def __ge__(self, o):
        return Symbol.create("broadcast_greater_equal", self, o)

    __hash__ = object.__hash__

    def __bool__(self):
        # reference: symbol.py:125 NotImplementedForSymbol — a lazy node
        # has no truth value; failing loudly beats silently-true
        raise TypeError("Symbol has no truth value (graphs are lazy); "
                        "compare inside the graph instead")

    def __getitem__(self, idx):
        if isinstance(idx, str):
            for out, name in zip(self._flat_outputs(),
                                 self.list_outputs()):
                if name == idx:
                    return out
            raise KeyError(idx)
        outs = self._flat_outputs()
        return outs[idx]

    def _flat_outputs(self):
        if self._op == "_group":
            return list(self._inputs)
        if self._nout == 1 or self._out_index is not None:
            return [self]
        return [Symbol(self._op, self._name, self._inputs, self._attrs,
                       nout=self._nout, out_index=i,
                       uattrs=self._uattrs)
                for i in range(self._nout)]

    # -- introspection -----------------------------------------------------
    @property
    def name(self):
        return self._name

    def attr(self, key):
        """Annotation attrs (strings — AttrScope / attr= / lr_mult-style
        kwargs) take priority; op kwargs come back raw for internal
        consumers (the onnx exporter reads tuples/ints through here)."""
        if key in self._uattrs:
            return self._uattrs[key]
        return self._attrs.get(key)

    def list_attr(self):
        """This node's annotation attrs (reference: Symbol.list_attr —
        shallow, strings). Variable annotations living in the op-kwarg
        store (`__shape__`/`__dtype__` from var(shape=..., dtype=...))
        are visible here like the reference, stringified."""
        out = {k: v if isinstance(v, str) else str(v)
               for k, v in self._attrs.items() if k.startswith("__")}
        out.update(self._uattrs)
        return out

    def attr_dict(self):
        """node name -> merged {op kwargs (stringified) + annotation
        attrs} for every node in the graph (reference: Symbol.attr_dict;
        test_attr.py:72 expects conv params AND propagated __dunder__
        attrs, and var shape/dtype/init annotations stay visible as
        `__shape__`/`__dtype__`/`__init__`)."""
        out = {}
        for s in self._topo():
            entry = {k: v if isinstance(v, str) else str(v)
                     for k, v in s._attrs.items()}
            entry.update(s._uattrs)
            if entry:
                merged = out.setdefault(s._name, {})
                merged.update(entry)
        return out

    def _topo(self):
        seen, order = set(), []

        def visit(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                visit(i)
            order.append(s)

        visit(self)
        return order

    def list_arguments(self):
        """Variable names in topo order (reference: list_arguments)."""
        out, seen = [], set()
        for s in self._topo():
            if s._op is None and s._name not in seen:
                seen.add(s._name)
                out.append(s._name)
        return out

    def list_inputs(self):
        return self.list_arguments()

    def list_outputs(self):
        if self._op == "_group":
            names = []
            for s in self._inputs:
                names.extend(s.list_outputs())
            return names
        if self._op is None:
            # variables keep their bare name (reference: internals
            # lookup spells sym.get_internals()['fc2_weight'])
            return [self._name]
        if self._nout == 1 or self._out_index is not None:
            suffix = "" if self._out_index in (None, 0) else \
                str(self._out_index)
            return [f"{self._name}_output{suffix}"]
        return [f"{self._name}_output{i}" for i in range(self._nout)]

    def get_internals(self):
        """All nodes as a multi-output group (reference: get_internals)."""
        return Group([s for s in self._topo() if s._op != "_group"])

    def __repr__(self):
        return f"<Symbol {self._name}>"

    # -- lowering to a pure function --------------------------------------
    def _lower(self):
        """Return fn(arg_dict) -> list of output arrays."""
        order = self._topo()

        def fn(arg_dict):
            vals = {}
            for s in order:
                if s._op is None:
                    if s._name not in arg_dict:
                        raise KeyError(f"missing argument {s._name!r}")
                    vals[id(s)] = arg_dict[s._name]
                elif s._op == "_group":
                    continue
                elif s._op == "_const":
                    vals[id(s)] = jnp.asarray(s._attrs["value"])
                else:
                    ins = [vals[id(i)] for i in s._inputs]
                    out = _op_fn(s._op)(ins, s._attrs)
                    if s._out_index is not None:
                        out = out[s._out_index]
                    vals[id(s)] = out
            if self._op == "_group":
                return [vals[id(s)] for s in self._inputs]
            out = vals[id(self)]
            if self._nout > 1 and self._out_index is None:
                return list(out)
            return [out]

        return fn

    # -- evaluation --------------------------------------------------------
    def eval(self, ctx=None, **kwargs):  # noqa: ARG002
        """Eager evaluation with named inputs (reference: Symbol.eval)."""
        from ..ndarray.ndarray import NDArray

        args = {k: v._data if isinstance(v, NDArray) else jnp.asarray(v)
                for k, v in kwargs.items()}
        outs = self._lower()(args)
        return [NDArray(o) for o in outs]

    def infer_shape(self, **kwargs):
        """(arg_shapes, out_shapes, aux_shapes) from input shapes.

        Reference: Symbol.infer_shape over nnvm InferShape
        (infer_graph_attr_pass.cc) — unknown ARG shapes are DEDUCED, not
        required: parameter shapes of the NN ops (FullyConnected weight/
        bias, Convolution, BatchNorm, Embedding) follow from the data
        shape, elementwise/broadcast operands unify dim-by-dim (0 = the
        reference's unknown-dim marker), and inconsistencies raise
        MXNetError. Fully-known subgraphs resolve through jax.eval_shape.
        """
        arg_shapes, out_shapes = _infer_shapes(self, kwargs, partial=False)
        return arg_shapes, out_shapes, []

    def infer_shape_partial(self, **kwargs):
        """Like infer_shape but unresolved entries come back as None
        instead of raising (reference: infer_shape_partial)."""
        arg_shapes, out_shapes = _infer_shapes(self, kwargs, partial=True)
        return arg_shapes, out_shapes, []

    def infer_type(self, *args, partial=False, **kwargs):
        """(arg_types, out_types, aux_types). Unspecified arguments take
        the common dtype of the specified ones (reference InferType
        propagates types through the graph: a float64 input makes the
        peer operand float64, infer_type_pass.cc), falling back to
        float32."""
        names = self.list_arguments()
        for n, t in zip(names, args):
            if t is not None:
                kwargs.setdefault(n, t)
        spec = {n: _np.dtype(kwargs[n]) for n in names
                if kwargs.get(n) is not None}
        uniq = set(spec.values())
        default = uniq.pop() if len(uniq) == 1 else _np.dtype(_np.float32)
        known = {n: jax.ShapeDtypeStruct((1,), spec.get(n, default))
                 for n in names}
        outs = jax.eval_shape(self._lower(), known)
        arg_types = [known[n].dtype if (n in spec or not partial) else None
                     for n in names]
        return (arg_types, [o.dtype for o in outs], [])

    def infer_type_partial(self, *args, **kwargs):
        """Like infer_type but unspecified arguments come back as None
        instead of a propagated guess (reference: infer_type_partial)."""
        return self.infer_type(*args, partial=True, **kwargs)

    def list_auxiliary_states(self):
        """Aux-state names (reference: list_auxiliary_states — BN running
        stats). This port keeps aux states as ordinary leaf arguments
        (they appear in list_arguments too, unlike the reference); this
        lists the subset by the canonical reference suffixes."""
        return [n for n in self.list_arguments()
                if n.endswith("_moving_mean") or n.endswith("_moving_var")]

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):  # noqa: ARG002
        """Build an Executor (reference: Symbol.bind → GraphExecutor; here
        the executor wraps a jitted function + jax.vjp). `aux_states`
        (list in list_auxiliary_states order, or dict) binds the BN
        running-stat leaves and is exposed as Executor.aux_dict."""
        return Executor(self, args or {}, args_grad, grad_req,
                        aux_states=aux_states)

    # reference 2.x renamed bind -> _bind (symbol.py _bind); tests and
    # migration guides use the underscore spelling
    _bind = bind

    def simple_bind(self, ctx=None, grad_req="write", **shape_kwargs):
        names = self.list_arguments()
        missing = [n for n in names if n not in shape_kwargs]
        if missing:
            # deduce parameter shapes from the given inputs (reference:
            # simple_bind runs InferShape and allocates every argument —
            # auto-created conv/fc params need no explicit shape)
            arg_shapes, _, _ = self.infer_shape_partial(**shape_kwargs)
            deduced = dict(zip(names, arg_shapes))
            for n in missing:
                if deduced.get(n) is None:
                    raise ValueError(
                        f"simple_bind needs shape for {n} "
                        "(not deducible from the given inputs)")
                shape_kwargs[n] = deduced[n]
        args = {n: jnp.zeros(shape_kwargs[n], jnp.float32) for n in names}
        return Executor(self, args, None, grad_req)

    # reference 2.x internal spelling (executor tests use it)
    _simple_bind = simple_bind

    # -- serialization -----------------------------------------------------
    def tojson(self):
        """Serialize the DAG (reference: model-symbol.json; node schema is
        ours — op/name/attrs/input ids — not nnvm's)."""
        order = [s for s in self._topo()]
        idx = {id(s): i for i, s in enumerate(order)}
        nodes = []
        for s in order:
            node = {
                "op": s._op, "name": s._name,
                "attrs": _json_attrs(s._attrs),
                "inputs": [idx[id(i)] for i in s._inputs],
                "nout": s._nout,
                "out_index": s._out_index,
            }
            if s._uattrs:
                node["uattrs"] = dict(s._uattrs)
            nodes.append(node)
        return json.dumps({"format": "mxnet_tpu-symbol", "version": 1,
                           "nodes": nodes, "head": idx[id(self)]}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # gradient symbol: not a graph pass here — executor.backward covers it
    def grad(self, wrt):
        raise NotImplementedError(
            "symbolic grad graphs are subsumed by Executor.backward "
            "(jax.vjp); bind() and call backward()")


def _json_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, _np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        else:
            out[k] = v
    return out


def _unjson_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = _np.asarray(v["__ndarray__"], dtype=v["dtype"])
        elif isinstance(v, list):
            out[k] = tuple(v)
        else:
            out[k] = v
    return out


def _const(value):
    arr = _np.asarray(value)
    return Symbol("_const", Symbol._auto_name("_const"), [],
                  {"value": arr})


register_sym_op("_const", lambda ins, attrs: jnp.asarray(attrs["value"]))
register_sym_op("_group", lambda ins, attrs: tuple(ins))


def var(name, shape=None, dtype=None, init=None, attr=None, **kwargs):
    """Create a variable (reference: symbol.var / Variable). `attr` and
    lr_mult-style kwargs become string annotation attrs (mirrored to
    __dunder__ spellings); the ambient AttrScope fills defaults
    (test_attr.py:23)."""
    from .. import attribute as _attr_mod

    attrs = {}
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(_np.dtype(dtype))
    user = dict(attr or {})
    user.update(kwargs)
    if init is not None:
        user.setdefault("__init__", str(init))
    # Variable KWARGS (lr_mult=..., reference var() specials) and
    # force_mirroring mirror to __dunder__; arbitrary attr= entries do
    # NOT grow phantom dunders (they would shadow the real
    # __dtype__/__shape__ channels and leak onto auto-created params)
    mirrored = {}
    for k, v in user.items():
        v = v if isinstance(v, str) else str(v)
        mirrored[k] = v
        if not k.startswith("__") and (k in Symbol._MIRROR_KEYS
                                       or k in kwargs
                                       or k == "force_mirroring"):
            mirrored[f"__{k}__"] = v
    uattrs = _attr_mod.current().get(mirrored)
    return Symbol(None, name, [], attrs, uattrs=uattrs)


Variable = var


def Group(symbols):
    """Multi-output symbol (reference: symbol.Group)."""
    flat = []
    for s in symbols:
        flat.extend(s._flat_outputs())
    return Symbol("_group", "group", flat)


def zeros(shape, dtype=_np.float32, **kwargs):  # noqa: ARG001
    return _const(_np.zeros(shape, dtype))


def ones(shape, dtype=_np.float32, **kwargs):  # noqa: ARG001
    return _const(_np.ones(shape, dtype))


def fromjson(js):
    data = json.loads(js)
    if data.get("format") != "mxnet_tpu-symbol":
        raise ValueError("not a mxnet_tpu symbol json")
    nodes = []
    for nd in data["nodes"]:
        nodes.append(Symbol(nd["op"], nd["name"],
                            [nodes[i] for i in nd["inputs"]],
                            _unjson_attrs(nd["attrs"]), nout=nd["nout"],
                            out_index=nd.get("out_index"),
                            uattrs=nd.get("uattrs")))
    return nodes[data["head"]]


load_json = fromjson


def load(fname):
    with open(fname) as f:
        return fromjson(f.read())


class Executor:
    """Bound graph (reference: executor.py over CachedOp). forward is the
    jitted lowered function; backward is jax.vjp at the same boundary."""

    def __init__(self, symbol, args, args_grad, grad_req, aux_states=None):
        from ..ndarray.ndarray import NDArray

        self._symbol = symbol
        self._names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        # reference bind accepts args/args_grad as a list (positional in
        # list_arguments order) or a dict (executor.py Bind)
        if isinstance(args, (list, tuple)):
            if len(args) != len(self._names):
                raise ValueError(
                    f"bind: {len(self._names)} arguments "
                    f"({self._names}) but {len(args)} arrays given")
            args = dict(zip(self._names, args))
        if isinstance(args_grad, (list, tuple)):
            if len(args_grad) != len(self._names):
                raise ValueError(
                    f"bind: list-form args_grad must cover all "
                    f"{len(self._names)} arguments (got "
                    f"{len(args_grad)}); use a dict for a subset")
            args_grad = dict(zip(self._names, args_grad))
        # aux_states (reference: bind's fourth array set) bind the BN
        # running-stat leaves; since this port keeps aux states in the
        # argument list, they merge into args (aux wins on conflict,
        # matching the reference where aux arrays are a separate store)
        if aux_states is not None:
            if isinstance(aux_states, (list, tuple)):
                if len(aux_states) != len(self._aux_names):
                    raise ValueError(
                        f"bind: {len(self._aux_names)} auxiliary states "
                        f"({self._aux_names}) but {len(aux_states)} "
                        "arrays given")
                aux_states = dict(zip(self._aux_names, aux_states))
            unknown = set(aux_states) - set(self._aux_names)
            if unknown:
                raise ValueError(
                    f"bind: unknown auxiliary states {sorted(unknown)}")
            args = dict(args)
            args.update(aux_states)
        self.arg_dict = {}
        for n in self._names:
            if n not in args:
                raise ValueError(f"bind missing argument {n}")
            v = args[n]
            self.arg_dict[n] = v if isinstance(v, NDArray) else \
                NDArray(jnp.asarray(v))
        # aliases the same NDArrays as arg_dict: updates through either
        # view hit the same buffers
        self.aux_dict = {n: self.arg_dict[n] for n in self._aux_names}
        self._grad_req = grad_req
        self.grad_dict = {n: None for n in self._names}
        if args_grad:
            for n, g in args_grad.items():
                self.grad_dict[n] = g
        lowered = symbol._lower()
        self._fn = jax.jit(lambda d: lowered(d))
        self._vjp = None
        self.outputs = []

    @property
    def arg_arrays(self):
        """Bound argument arrays in list_arguments order (reference:
        executor.py arg_arrays)."""
        return [self.arg_dict[n] for n in self._names]

    @property
    def grad_arrays(self):
        return [self.grad_dict[n] for n in self._names]

    @property
    def aux_arrays(self):
        """Bound auxiliary-state arrays in list_auxiliary_states order
        (reference: executor.py aux_arrays)."""
        return [self.aux_dict[n] for n in self._aux_names]

    def forward(self, is_train=False, **kwargs):
        from ..ndarray.ndarray import NDArray

        for n, v in kwargs.items():
            self.arg_dict[n] = v if isinstance(v, NDArray) else \
                NDArray(jnp.asarray(v))
            if n in self._aux_names:
                self.aux_dict[n] = self.arg_dict[n]
        data = {n: a._data for n, a in self.arg_dict.items()}
        if is_train:
            outs, self._vjp = jax.vjp(self._fn, data)
        else:
            outs = self._fn(data)
            self._vjp = None
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        from ..ndarray.ndarray import NDArray

        if self._vjp is None:
            # reference permits backward after a plain forward() — the
            # gradient pass re-linearizes at the current bindings
            if not self.outputs:
                raise RuntimeError("call forward() first")
            data = {n: a._data for n, a in self.arg_dict.items()}
            _, self._vjp = jax.vjp(self._fn, data)
        if out_grads is None:
            cts = [jnp.ones_like(o._data) for o in self.outputs]
        else:
            if not isinstance(out_grads, (list, tuple)):
                out_grads = [out_grads]
            cts = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                   for g in out_grads]
        (grads,) = self._vjp(cts)
        for n in self._names:
            g = grads.get(n)
            if g is None or self._grad_req == "null":
                continue
            buf = self.grad_dict[n]
            if self._grad_req == "add" and buf is not None:
                buf._assign_from(NDArray(buf._data + g))
            elif buf is not None:
                # gradients land IN the caller's bound grad arrays
                # (reference: args_grad buffers are written in place)
                buf._assign_from(NDArray(g))
            else:
                self.grad_dict[n] = NDArray(g)
        return self.grad_dict


# ---------------------------------------------------------------------------
# shape inference (reference: nnvm InferShape, infer_graph_attr_pass.cc)
# ---------------------------------------------------------------------------

# equal-shape contract ops only (reference ElemwiseShape); broadcast_*
# ops accept dim-1/rank-promoted operands and must NOT dim-unify
_ELEMWISE_UNIFY = {
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "_identity_with_attr_like_rhs",
}


def _unify_dims(a, b, what):
    """Merge two shapes dim-by-dim; 0 means unknown (reference shape
    convention). Conflict -> MXNetError."""
    from ..base import MXNetError

    if a is None:
        return tuple(b) if b is not None else None
    if b is None:
        return tuple(a)
    if len(a) != len(b):
        raise MXNetError(
            f"infer_shape: rank mismatch at {what}: {a} vs {b}")
    out = []
    for da, db in zip(a, b):
        if da == 0:
            out.append(db)
        elif db == 0 or da == db:
            out.append(da)
        else:
            raise MXNetError(
                f"infer_shape: inconsistent shapes at {what}: {a} vs {b}")
    return tuple(out)


def _shape_known(s):
    return s is not None and all(d != 0 for d in s)


def _deduce_params(node, shapes, record):
    """Parameter-shape deduction for the curated NN ops: given the data
    shape, fill in unknown weight/bias/stat leaf shapes (reference: each
    op's InferShape filling in_shape backward)."""
    op = node._op
    ins = node._inputs
    data_shape = shapes.get(id(ins[0]))
    if data_shape is None or not _shape_known(data_shape):
        return
    a = node._attrs

    def put(sym, shape, what):
        shapes[id(sym)] = _unify_dims(shapes.get(id(sym)), shape, what)
        record(sym)

    if op == "FullyConnected" and a.get("num_hidden"):
        nh = int(a["num_hidden"])
        in_units = data_shape[-1] if not a.get("flatten", True) else \
            int(_np.prod(data_shape[1:]))
        put(ins[1], (nh, in_units), f"{node._name}.weight")
        if len(ins) > 2:
            put(ins[2], (nh,), f"{node._name}.bias")
    elif op in ("Convolution", "Deconvolution") and a.get("num_filter") \
            and a.get("kernel"):
        nf = int(a["num_filter"])
        kern = tuple(int(k) for k in a["kernel"])
        grp = int(a.get("num_group", 1) or 1)
        c = data_shape[1]
        if op == "Convolution":
            w_shape = (nf, c // grp) + kern
        else:  # Deconvolution: weight is (C_in, num_filter/group, *k)
            w_shape = (c, nf // grp) + kern
        put(ins[1], w_shape, f"{node._name}.weight")
        if len(ins) > 2:
            put(ins[2], (nf,), f"{node._name}.bias")
    elif op in ("BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm"):
        # runtime ops: BatchNorm/InstanceNorm/GroupNorm scale the channel
        # axis (1); LayerNorm normalizes the last axis (ops/nn.py)
        axis = int(a.get("axis", -1 if op == "LayerNorm" else 1))
        c = data_shape[axis]
        for i in range(1, len(ins)):
            put(ins[i], (c,), f"{node._name}.param{i}")
    elif op == "Embedding" and a.get("input_dim") and a.get("output_dim"):
        put(ins[1], (int(a["input_dim"]), int(a["output_dim"])),
            f"{node._name}.weight")


def _infer_shapes(sym, kwargs, partial):
    from ..base import MXNetError

    order = sym._topo()
    shapes = {}  # id(node) -> tuple (0 = unknown dim) or None
    leaves = {}
    for s in order:
        if s._op is None:
            leaves.setdefault(s._name, []).append(s)
            declared = s._attrs.get("__shape__")
            if declared is not None:
                shapes[id(s)] = tuple(declared)
        elif s._op == "_const":
            shapes[id(s)] = tuple(_np.asarray(s._attrs["value"]).shape)
    for k, v in kwargs.items():
        shp = tuple(v) if isinstance(v, (tuple, list)) else tuple(v.shape)
        for leaf in leaves.get(k, ()):
            shapes[id(leaf)] = _unify_dims(shapes.get(id(leaf)), shp, k)

    def record(sym_):  # same-named leaves share their deduction
        if sym_._op is None:
            for twin in leaves.get(sym_._name, ()):
                shapes[id(twin)] = _unify_dims(
                    shapes.get(id(twin)), shapes[id(sym_)], sym_._name)

    # iterate to a fixpoint: deduction on one node may complete the
    # inputs of another (two passes suffice for feed-forward DAGs; loop
    # until stable for safety)
    for _ in range(len(order)):
        changed = False
        for s in order:
            if s._op in (None, "_const", "_group"):
                continue
            before = shapes.get(id(s))
            _deduce_params(s, shapes, record)
            if s._op in _ELEMWISE_UNIFY and len(s._inputs) >= 2:
                # unify only same-rank operands; a scalar _const riding a
                # broadcast (x * 2) participates in VALUE lowering but
                # not in the equal-shape contract
                known = [shapes.get(id(i)) for i in s._inputs]
                ranks = {len(k) for k in known if k is not None}
                uni = None
                if len(ranks) == 1:
                    for si in known:
                        if si is not None:
                            uni = _unify_dims(uni, si, s._name)
                if uni is not None:
                    for i in s._inputs:
                        if i._op is None:  # write back to variables only
                            shapes[id(i)] = _unify_dims(
                                shapes.get(id(i)), uni, s._name)
                            record(i)
                    if _shape_known(uni):
                        shapes[id(s)] = uni
            if shapes.get(id(s)) is None \
                    and (id(s), "multi") not in shapes and all(
                    _shape_known(shapes.get(id(i))) for i in s._inputs):
                # fully-known inputs: one-op abstract eval
                ins_sds = [jax.ShapeDtypeStruct(shapes[id(i)], jnp.float32)
                           for i in s._inputs]
                try:
                    out = jax.eval_shape(
                        lambda *xs, _s=s: _op_fn(_s._op)(list(xs),
                                                         _s._attrs),
                        *ins_sds)
                except Exception as e:  # shape-invalid graph
                    raise MXNetError(
                        f"infer_shape failed at {s._name} ({s._op}): {e}"
                    ) from e
                if s._out_index is not None:
                    out = out[s._out_index]
                shapes[id(s)] = tuple(out.shape) if hasattr(out, "shape") \
                    else tuple(out[0].shape)  # multi-out: first's shape
                if s._nout > 1 and s._out_index is None:
                    shapes[id(s)] = None  # handled via sliced wrappers
                    shapes[(id(s), "multi")] = [tuple(o.shape)
                                                for o in out]
            if shapes.get(id(s)) != before:
                changed = True
        if not changed:
            break

    names = sym.list_arguments()
    arg_shapes = []
    for n in names:
        leaf = leaves[n][0]
        shp = shapes.get(id(leaf))
        if not _shape_known(shp):
            if not partial:
                raise MXNetError(
                    f"infer_shape could not resolve argument {n!r} "
                    f"(got {shp}); provide its shape or use "
                    "infer_shape_partial")
            shp = None
        arg_shapes.append(shp)

    if sym._op == "_group":
        outs = sym._flat_outputs()
    elif sym._nout > 1 and sym._out_index is None:
        # bare multi-output head: one shape per output, from the node's
        # 'multi' record (the fresh _flat_outputs wrappers have new ids)
        ms = shapes.get((id(sym), "multi"))
        outs = list(range(sym._nout))
        out_shapes = []
        for i in outs:
            shp = ms[i] if ms is not None else None
            if not _shape_known(shp):
                if not partial:
                    raise MXNetError(
                        f"infer_shape could not resolve output {i} of "
                        f"{sym._name}")
                shp = None
            out_shapes.append(shp)
        return arg_shapes, out_shapes
    else:
        outs = [sym]
    out_shapes = []
    for o in outs:
        shp = shapes.get(id(o))
        if shp is None and (id(o), "multi") in shapes:
            shp = shapes[(id(o), "multi")][o._out_index or 0]
        if not _shape_known(shp):
            if not partial:
                raise MXNetError(
                    f"infer_shape could not resolve output of {o._name}")
            shp = None
        out_shapes.append(shp)
    return arg_shapes, out_shapes
