"""`mx.sym.random` namespace (reference: mxnet/symbol/random.py).

Symbol graphs here are deterministic lowerings (export/SymbolBlock), so
random nodes carry an explicit integer `seed` attr: the node is a pure
function of (shape, seed) — reproducible across executions and faithful
under graph serialization. Stateful per-call randomness belongs to the
imperative frontend (mx.np.random / mx.random)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .symbol import Symbol, register_sym_op

__all__ = ["uniform", "normal", "randint", "gamma", "exponential"]


def _key(attrs):
    return jax.random.PRNGKey(int(attrs.get("seed", 0)))


def _shape(attrs):
    s = attrs.get("shape", (1,))
    if isinstance(s, (int, float)):
        s = (int(s),)
    return tuple(int(d) for d in s)


def _dt(attrs):
    return jnp.dtype(str(attrs.get("dtype", "float32")))


register_sym_op("random_uniform", lambda ins, a: jax.random.uniform(
    _key(a), _shape(a), _dt(a), float(a.get("low", 0.0)),
    float(a.get("high", 1.0))))
register_sym_op("random_normal", lambda ins, a: (
    float(a.get("loc", 0.0)) + float(a.get("scale", 1.0))
    * jax.random.normal(_key(a), _shape(a), _dt(a))))
register_sym_op("random_randint", lambda ins, a: jax.random.randint(
    _key(a), _shape(a), int(a.get("low", 0)), int(a.get("high", 2))))
register_sym_op("random_gamma", lambda ins, a: jax.random.gamma(
    _key(a), float(a.get("alpha", 1.0)), _shape(a)) *
    float(a.get("beta", 1.0)))
register_sym_op("random_exponential", lambda ins, a: jax.random.exponential(
    _key(a), _shape(a)) / float(a.get("lam", 1.0)))


def _make(short, full):
    def wrapper(shape=(1,), seed=0, name=None, **attrs):
        return Symbol.create(full, shape=tuple(shape), seed=int(seed),
                             name=name, **attrs)

    wrapper.__name__ = short
    wrapper.__doc__ = (f"Symbol builder for {full}; pure function of "
                       "(shape, seed) — see module docstring.")
    return wrapper


uniform = _make("uniform", "random_uniform")
normal = _make("normal", "random_normal")
randint = _make("randint", "random_randint")
gamma = _make("gamma", "random_gamma")
exponential = _make("exponential", "random_exponential")
