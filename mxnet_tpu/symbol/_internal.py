"""Internal symbol-op namespace (reference: mxnet/symbol/_internal.py).
Resolves through the symbol op table."""


def __getattr__(name):
    from . import op as _sop

    for cand in (name, name.lstrip("_")):
        fn = getattr(_sop, cand, None)
        if fn is not None:
            return fn
    raise AttributeError(f"no symbol op {name!r}")
