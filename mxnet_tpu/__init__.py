"""mxnet_tpu — a TPU-native deep learning framework with MXNet's capabilities.

Ground-up JAX/XLA re-design of Apache MXNet (reference: Adnios/incubator-mxnet,
see SURVEY.md): imperative NDArray/NumPy frontends with an eager autograd tape,
Gluon Block/HybridBlock model authoring where hybridize() compiles traced
subgraphs with jax.jit (the CachedOp analog), `mx.tpu()` device contexts over
PJRT, optimizers as fused on-device update fns, and `kvstore='tpu_dist'`
data-parallel training over ICI via XLA collectives.

Usage mirrors the reference:

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, np, npx

    x = mx.np.ones((2, 3), device=mx.tpu(0))
    net = mx.gluon.nn.Dense(4)
    net.initialize()
    with autograd.record():
        y = net(x).sum()
    y.backward()
"""
from __future__ import annotations

__version__ = "0.1.0"

# 64-bit dtype contract (reference: mshadow DType dispatch supports real
# float64/int64 compute; shape_array returns int64 —
# src/operator/tensor/matrix_op.cc). Explicit 64-bit requests are honored;
# every creation default in this package stays float32/int32 like the
# reference's. fp64 is emulated (slow) on TPU — fine for CPU parity work,
# documented in docs/migration.md.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

from . import _jax_defaults as _jax_defaults_mod

_jax_defaults_mod.install()  # 32-bit defaults on dtype-less jax.random

from . import autograd, base, device, engine
from . import env  # typed env-var registry (env_var.md analog)
from . import _random
from .base import MXNetError
from .device import (
    Context,
    Device,
    cpu,
    cpu_pinned,
    current_device,
    gpu,
    num_gpus,
    num_tpus,
    tpu,
)
from . import ndarray
from . import ndarray as nd
from . import numpy as np  # noqa: A004 - intentional: mx.np
from . import numpy_extension as npx
from .ndarray import NDArray

# random: stateful global seed + legacy mx.random namespace
from . import random  # noqa: E402  (module == mx.random attr)

# subpackages loaded lazily-ish but imported eagerly for API parity
from . import initializer  # noqa: E402
from . import optimizer  # noqa: E402
from . import lr_scheduler  # noqa: E402
from . import kvstore as kv  # noqa: E402
from . import kvstore  # noqa: E402
from . import io  # noqa: E402
from . import image  # noqa: E402
from . import attribute  # noqa: E402
from . import callback  # noqa: E402
from . import contrib  # noqa: E402
from . import library  # noqa: E402
from . import model  # noqa: E402
from . import monitor  # noqa: E402
from . import name  # noqa: E402
from . import onnx  # noqa: E402
from . import visualization  # noqa: E402
from .attribute import AttrScope  # noqa: E402
from .monitor import Monitor  # noqa: E402
from .name import NameManager  # noqa: E402
from .visualization import plot_network, print_summary  # noqa: E402
from . import operator  # noqa: E402
from .operator import Custom  # noqa: E402
from . import recordio  # noqa: E402
from . import resource  # noqa: E402
from . import rtc  # noqa: E402
from . import context  # noqa: E402
from . import dlpack  # noqa: E402
from . import error  # noqa: E402
from . import executor  # noqa: E402
from . import libinfo  # noqa: E402
from . import log  # noqa: E402
from . import registry  # noqa: E402
from . import gluon  # noqa: E402
from . import symbol  # noqa: E402
from . import symbol as sym  # noqa: E402
from . import storage  # noqa: E402
from . import contrib  # noqa: E402
from . import util  # noqa: E402
from . import runtime  # noqa: E402
from . import profiler  # noqa: E402
from . import telemetry  # noqa: E402  (runtime metrics; docs/telemetry.md)
from . import passes  # noqa: E402  (graph-pass pipeline; docs/passes.md)
from . import diagnostics  # noqa: E402  (spans/compile introspection/watchdog)
from . import test_utils  # noqa: E402  (mx.test_utils like the reference)
from . import amp  # noqa: E402  (mx.amp — reference: python/mxnet/amp/)
from . import serving  # noqa: E402  (batching inference engine; docs/serving.md)
from . import decode  # noqa: E402  (KV-cache autoregressive decode; docs/decode.md)
from . import checkpoint  # noqa: E402  (atomic snapshots; docs/checkpointing.md)
from . import sharding  # noqa: E402  (hybrid parallelism; docs/sharding.md)
from . import elastic  # noqa: E402  (topology-change survival; docs/elasticity.md)
from . import observability  # noqa: E402  (flight recorder + numerics + postmortems)

waitall = engine.waitall


def seed(s, ctx="all"):
    """Seed all framework RNGs (reference: mx.random.seed)."""
    _random.seed(s, ctx)


# Internal reference spellings (_npi_*, _contrib_*, _plus_scalar, ...)
# resolve onto the same registry entries as the public names.
from .ops.aliases import install_aliases as _install_aliases  # noqa: E402

_install_aliases()

__all__ = [
    "NDArray",
    "MXNetError",
    "Context",
    "Device",
    "cpu",
    "cpu_pinned",
    "gpu",
    "tpu",
    "num_gpus",
    "num_tpus",
    "current_device",
    "autograd",
    "nd",
    "np",
    "npx",
    "ndarray",
    "gluon",
    "initializer",
    "optimizer",
    "lr_scheduler",
    "kvstore",
    "kv",
    "random",
    "seed",
    "waitall",
    "engine",
    "symbol",
    "sym",
    "storage",
    "contrib",
    "device",
    "base",
    "util",
    "runtime",
    "profiler",
    "telemetry",
    "diagnostics",
    "observability",
]
