"""Device / Context abstraction.

Re-design of the reference's `python/mxnet/device.py` (Context/Device) for TPU:
`mx.tpu(i)` resolves to a PJRT TPU device; `mx.cpu()` to host. The reference's
`mx.gpu(i)` is kept as an alias for "the i-th accelerator" so models written
against the MXNet API keep running.

Device placement semantics: creation ops honor the *current device* (a
thread-local stack, entered with `with mx.Device('tpu', 0):` exactly like the
reference's `with mx.Context(...)`). Compute follows its inputs (XLA runs the op
where the operands live), matching the reference's "ops run on the context of
their inputs" rule (src/imperative/imperative_utils.h GetContext).
"""
from __future__ import annotations

import threading
import warnings

import jax

__all__ = ["Device", "Context", "cpu", "gpu", "tpu", "cpu_pinned", "num_gpus",
           "num_tpus", "current_device", "default_device"]

_DEVTYPE_ALIASES = {
    "cpu_pinned": "cpu",
    "cpu_shared": "cpu",
}

# Accelerator device types: resolve to the default-backend accelerator. 'gpu' is
# accepted for reference-API compatibility and resolves to the accelerator
# backend actually present (tpu here).
_ACCEL_TYPES = ("tpu", "gpu", "cuda")


class Device:
    """A device descriptor, hashable and comparable.

    Also usable as a context manager to set the default creation device,
    mirroring `with mx.Context(...)` in the reference
    (python/mxnet/device.py:Device.__enter__).
    """

    _tls = threading.local()
    _warned_fallback = set()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Device):
            device_id = device_type.device_id
            device_type = device_type.device_type
        device_type = _DEVTYPE_ALIASES.get(device_type, device_type)
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- identity ---------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Device)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- resolution to a PJRT device -------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax (PJRT) device.

        If the requested platform is absent (e.g. `tpu(0)` in a CPU-mesh test
        run), fall back to the default backend's devices so code written for
        TPU runs anywhere; warn once per platform.
        """
        # NB: local_devices, not jax.devices() — under jax.distributed the
        # global list spans all processes and devices of other ranks are
        # non-addressable; mx.cpu(0)/mx.tpu(0) always mean THIS process's
        # devices (the reference's per-worker ctx semantics).
        dt = self.device_type
        if dt in _ACCEL_TYPES:
            try:
                devs = jax.local_devices(backend="tpu")
            except RuntimeError:
                devs = None
            if not devs:
                try:
                    devs = jax.local_devices(backend="gpu")
                except RuntimeError:
                    devs = None
            if not devs:
                if dt not in Device._warned_fallback:
                    Device._warned_fallback.add(dt)
                    warnings.warn(
                        f"device type '{dt}' not available; falling back to "
                        f"default backend '{jax.default_backend()}'",
                        stacklevel=2,
                    )
                devs = jax.local_devices()
        else:
            devs = jax.local_devices(backend=dt)
        return devs[self.device_id % len(devs)]

    # -- default-device stack --------------------------------------------
    def __enter__(self):
        stack = getattr(Device._tls, "stack", None)
        if stack is None:
            stack = Device._tls.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        Device._tls.stack.pop()
        return False


# The reference calls this class Context in 1.x and Device in 2.x; keep both.
Context = Device


def cpu(device_id=0):
    """Return a CPU device."""
    return Device("cpu", device_id)


def cpu_pinned(device_id=0):
    """Pinned host memory context (parity alias; host memory on TPU hosts)."""
    return Device("cpu", device_id)


def tpu(device_id=0):
    """Return the i-th TPU device — the native accelerator context."""
    return Device("tpu", device_id)


def gpu(device_id=0):
    """Reference-compat alias: the i-th accelerator (TPU here)."""
    return Device("gpu", device_id)


def _accel_count():
    try:
        return len(jax.devices("tpu"))
    except RuntimeError:
        pass
    try:
        return len(jax.devices("gpu"))
    except RuntimeError:
        return 0


def num_gpus():
    """Number of accelerator devices (reference: mx.device.num_gpus)."""
    return _accel_count()


def num_tpus():
    """Number of TPU devices visible to this process."""
    return _accel_count()


def current_device():
    """The device new arrays are created on (innermost `with device:` scope)."""
    stack = getattr(Device._tls, "stack", None)
    if stack:
        return stack[-1]
    return default_device()


_default = None


def default_device():
    """Process default: the first accelerator if present, else cpu."""
    global _default
    if _default is None:
        backend = jax.default_backend()
        _default = Device("tpu" if backend in ("tpu", "gpu") else "cpu", 0)
    return _default


def from_jax_device(d):
    """Map a concrete jax device back to a Device descriptor."""
    plat = d.platform
    if plat in ("tpu", "gpu"):
        return Device("tpu", d.id)
    return Device("cpu", d.id)
