"""Profiler: XLA/XPlane device traces + host-side chrome-trace events.

Reference: src/profiler/ (typed stats in per-device buffers dumped as Chrome
chrome://tracing JSON + aggregate summaries, python/mxnet/profiler.py).

TPU re-design: two complementary layers —
  * device time: jax.profiler traces (XPlane) capture XLA compute, HBM
    transfers, and collectives for TensorBoard/Perfetto, replacing the
    engine-op timeline (set_state('run'/'stop'));
  * host time: Task/Event/Frame/Counter and `scope()` record host-side
    spans into an in-memory buffer that dump() writes as the same Chrome
    trace-event JSON the reference emitted (profiler.dump → profile.json,
    viewable at chrome://tracing), and dumps() aggregates like
    aggregate_stats (count/total/min/max per name).
`scope()` additionally enters jax.named_scope, so the same name shows up
attached to HLO ops inside the device trace.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

import jax

_config = {"filename": "profile.json", "profile_all": False,
           "aggregate_stats": True}
_running = False
_paused = False
_trace_dir = None

_events = []  # chrome trace events: dicts with name/ph/ts/dur/pid/tid
_events_lock = threading.Lock()
_t_origin = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t_origin) * 1e6


def _host_recording():
    """Host events record only while the profiler runs (reference: nothing
    is recorded before set_state('run')) or with profile_all set."""
    return (_running or _config.get("profile_all")) and not _paused


def _record(name, t0_us, dur_us, cat="host"):
    if not _host_recording():
        return
    with _events_lock:
        _events.append({
            "name": name, "cat": cat, "ph": "X", "ts": t0_us,
            "dur": dur_us, "pid": os.getpid(),
            "tid": threading.get_ident() % 100000,
        })


def perf_counter_to_trace_us(t):
    """Convert a raw ``time.perf_counter()`` reading to this trace's
    microsecond timeline (diagnostics spans store perf_counter stamps and
    replay them here, so both layers share one clock origin)."""
    return (t - _t_origin) * 1e6


def record_host_event(name, ts_us, dur_us, cat="host", args=None):
    """Append a complete chrome "X" event to the host buffer — the
    diagnostics span bridge's entry point, gated like every host event.
    Returns 1 if recorded, 0 if not recording."""
    if not _host_recording():
        return 0
    ev = {"name": name, "cat": cat, "ph": "X", "ts": ts_us,
          "dur": dur_us, "pid": os.getpid(),
          "tid": threading.get_ident() % 100000}
    if args:
        ev["args"] = dict(args)
    with _events_lock:
        _events.append(ev)
    return 1


def record_counter_event(name, value, cat="telemetry"):
    """Append a chrome counter event (`"ph": "C"`) to the host buffer —
    the telemetry bridge's entry point (telemetry/chrome.py), gated like
    every host event. Returns 1 if recorded, 0 if not recording."""
    if not _host_recording():
        return 0
    with _events_lock:
        _events.append({"name": name, "cat": cat, "ph": "C",
                        "ts": _now_us(), "pid": os.getpid(),
                        "args": {"value": float(value)}})
    return 1


def set_config(**kwargs):
    """Accepts reference kwargs (filename, profile_all, aggregate_stats...)."""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):  # noqa: ARG001
    global _running, _trace_dir
    if state == "run" and not _running:
        _trace_dir = _config.get("trace_dir") or os.path.join(
            os.path.dirname(os.path.abspath(_config["filename"])) or ".",
            "jax_trace",
        )
        jax.profiler.start_trace(_trace_dir)
        _running = True
    elif state == "stop" and _running:
        jax.profiler.stop_trace()
        _running = False


def start():
    set_state("run")


def stop():
    set_state("stop")


def dump(finished=True, profile_process="worker"):  # noqa: ARG001
    """Write host-side events as Chrome trace JSON to `filename`
    (reference: MXDumpProfile → chrome://tracing file); stops any live
    device trace first."""
    if _running:
        stop()
    with _events_lock:
        events = list(_events)
        _events.clear()  # dumped events are consumed (bounded memory)
    with open(_config["filename"], "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return _config["filename"]


def dumps(reset=False):
    """Aggregate summary table (reference: aggregate_stats dumps)."""
    with _events_lock:
        events = list(_events)
        if reset:
            _events.clear()
    agg = {}
    for e in events:
        if e.get("ph") != "X":  # counters carry no duration
            continue
        a = agg.setdefault(e["name"], [0, 0.0, float("inf"), 0.0])
        a[0] += 1
        a[1] += e["dur"]
        a[2] = min(a[2], e["dur"])
        a[3] = max(a[3], e["dur"])
    lines = [f"{'Name':<32}{'Count':>8}{'Total(ms)':>12}{'Min(ms)':>10}"
             f"{'Max(ms)':>10}"]
    for name, (cnt, tot, mn, mx) in sorted(agg.items()):
        lines.append(f"{name:<32}{cnt:>8}{tot / 1e3:>12.3f}"
                     f"{mn / 1e3:>10.3f}{mx / 1e3:>10.3f}")
    if _trace_dir:
        lines.append(f"device trace dir: {_trace_dir}")
    return "\n".join(lines)


@contextlib.contextmanager
def scope(name="<unk>"):
    """Name scope: annotates HLO (device trace) and records a host span
    (reference: profiler.Scope / ProfilerScope, profiler.h:1339)."""
    t0 = _now_us()
    try:
        with jax.named_scope(name):
            yield
    finally:
        # record even when the body raises — the failing region is exactly
        # the one worth seeing in the trace
        _record(f"scope::{name}", t0, _now_us() - t0)


class Task:
    """Named task timing (reference: profiler.Task) — host wall timing,
    recorded into the chrome trace on each stop."""

    _kind = "task"

    def __init__(self, name, domain=None):  # noqa: ARG002
        self.name = name
        self._t0 = None
        self.elapsed = 0.0

    def start(self):
        self._t0 = time.perf_counter()
        self._ts_us = _now_us()

    def stop(self):
        if self._t0 is not None:
            dur = time.perf_counter() - self._t0
            self.elapsed += dur
            _record(f"{self._kind}::{self.name}", self._ts_us, dur * 1e6)
            self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Frame(Task):
    _kind = "frame"


class Event(Task):
    _kind = "event"


class Counter:
    """Named counter (reference: profiler.Counter); value changes are
    recorded as chrome counter events."""

    def __init__(self, name, domain=None, value=0):  # noqa: ARG002
        self.name = name
        self.value = value

    def _emit(self):
        if _host_recording():
            with _events_lock:
                _events.append({"name": f"counter::{self.name}", "ph": "C",
                                "ts": _now_us(), "pid": os.getpid(),
                                "args": {"value": self.value}})

    def set_value(self, v):
        self.value = v
        self._emit()

    def increment(self, delta=1):
        self.value += delta
        self._emit()

    def decrement(self, delta=1):
        self.value -= delta
        self._emit()

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


def pause(profile_process="worker"):  # noqa: ARG001
    global _paused
    _paused = True


def resume(profile_process="worker"):  # noqa: ARG001
    global _paused
    _paused = False


class Marker:
    """Instant marker (reference: profiler.Marker — mark() drops an
    instant event into the trace)."""

    def __init__(self, name, domain=None):  # noqa: ARG002
        self.name = name

    def mark(self, scope="process"):
        if _host_recording():
            with _events_lock:
                _events.append({"name": f"marker::{self.name}", "ph": "i",
                                "ts": _now_us(), "pid": os.getpid(),
                                "s": {"process": "p", "thread": "t",
                                      "global": "g"}.get(scope, "p")})


class Domain:
    """Named grouping for profiler objects (reference: profiler.Domain —
    a factory whose name prefixes everything created under it)."""

    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_counter(self, name, value=0):
        return Counter(f"{self.name}::{name}", self, value)

    def new_task(self, name):
        return Task(f"{self.name}::{name}", self)

    def new_frame(self, name):
        return Frame(f"{self.name}::{name}", self)

    def new_event(self, name):
        return Event(f"{self.name}::{name}", self)

    def new_marker(self, name):
        return Marker(f"{self.name}::{name}", self)

