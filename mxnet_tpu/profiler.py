"""Profiler over jax.profiler / XPlane.

Reference: src/profiler/ (Chrome-trace JSON dump of engine ops) +
python/mxnet/profiler.py. The TPU analog is the XLA profiler: traces capture
device compute, HBM transfers, and collectives, viewable in TensorBoard or
Perfetto. The op-name scoping mechanism (ProfilerScope, profiler.h:1339) maps
to jax.named_scope, which annotates HLO and shows up in the trace.
"""
from __future__ import annotations

import contextlib
import os
import time

import jax

_config = {"filename": "profile.json", "profile_all": False}
_running = False
_trace_dir = None


def set_config(**kwargs):
    """Accepts reference kwargs (filename, profile_all, aggregate_stats...)."""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):  # noqa: ARG001
    global _running, _trace_dir
    if state == "run" and not _running:
        _trace_dir = _config.get("trace_dir") or os.path.join(
            os.path.dirname(os.path.abspath(_config["filename"])) or ".",
            "jax_trace",
        )
        jax.profiler.start_trace(_trace_dir)
        _running = True
    elif state == "stop" and _running:
        jax.profiler.stop_trace()
        _running = False


def start():
    set_state("run")


def stop():
    set_state("stop")


def dump(finished=True, profile_process="worker"):  # noqa: ARG001
    """Trace data is written by stop_trace; kept for API parity."""
    if _running:
        stop()


def dumps(reset=False):  # noqa: ARG001
    return f"trace dir: {_trace_dir}" if _trace_dir else "profiler not run"


@contextlib.contextmanager
def scope(name="<unk>"):
    """Name scope annotating HLO ops (reference: profiler.Scope)."""
    with jax.named_scope(name):
        yield


class Task:
    """Named task timing (reference: profiler.Task) — host-side wall timing."""

    def __init__(self, name, domain=None):  # noqa: ARG002
        self.name = name
        self._t0 = None
        self.elapsed = 0.0

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is not None:
            self.elapsed += time.perf_counter() - self._t0
            self._t0 = None


Frame = Task
Event = Task


class Counter:
    def __init__(self, name, domain=None, value=0):  # noqa: ARG002
        self.name = name
        self.value = value

    def set_value(self, v):
        self.value = v

    def increment(self, delta=1):
        self.value += delta

    def decrement(self, delta=1):
        self.value -= delta


def pause(profile_process="worker"):  # noqa: ARG001
    pass


def resume(profile_process="worker"):  # noqa: ARG001
    pass
