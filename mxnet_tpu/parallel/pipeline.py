"""Pipeline parallelism: GPipe-style microbatched stage execution over a
'pp' mesh axis.

New capability beyond the reference (SURVEY §2.4: its closest artifact is
a manual model-parallel LSTM recipe). Stage parameters are stacked on a
leading stage dimension and sharded over 'pp'; inside `shard_map` each
device runs its own stage and hands activations to the next stage with
`ppermute` over ICI. The schedule is the classic GPipe fill-drain loop:
`n_micro + n_stages - 1` ticks, bubble fraction (S-1)/(M+S-1).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["pipeline_apply", "pipeline_apply_sharded"]


def pipeline_apply(stage_fn, stacked_params, microbatches, axis_name):
    """Run inside shard_map/pmap over `axis_name` (one device = one
    stage).

    stage_fn(params, x) -> y applies one stage; stacked_params has a
    leading stage dim already sharded to size 1 per device (shard_map
    gives the local slice WITH the dim). microbatches: (M, ...) —
    replicated; every stage sees all microbatches, stage 0 consumes
    them, later stages consume ppermuted activations. Returns (M, ...)
    stage outputs valid on the LAST stage (zeros elsewhere).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage_id = jax.lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    local_params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)

    # probe output shape: activations between stages share the
    # microbatch shape (standard GPipe homogeneous-stage contract)
    out_shape = jax.eval_shape(stage_fn, local_params, microbatches[0])
    carry = jnp.zeros(out_shape.shape, out_shape.dtype)
    outputs = jnp.zeros((n_micro,) + out_shape.shape, out_shape.dtype)
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(state, t):
        carry, outputs = state
        # stage 0 feeds microbatch t (when in range); others use carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x = jnp.where(stage_id == 0,
                      microbatches[mb_idx], carry)
        y = stage_fn(local_params, x)
        # valid iff this stage is currently processing a real microbatch:
        # stage s works on microbatch t - s
        mb_of_stage = t - stage_id
        valid = (mb_of_stage >= 0) & (mb_of_stage < n_micro)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        # last stage records its finished microbatch
        out_idx = jnp.clip(mb_of_stage, 0, n_micro - 1)
        record = valid & (stage_id == n_stages - 1)
        outputs = jax.lax.cond(
            record,
            lambda o: o.at[out_idx].set(y),
            lambda o: o,
            outputs)
        # hand activations to the next stage
        carry = jax.lax.ppermute(y, axis_name, perm)
        return (carry, outputs), None

    total = n_micro + n_stages - 1
    # scan (not fori_loop) so the schedule is reverse-differentiable —
    # pipelined BACKWARD falls out of jax.grad through the same loop
    (_, outputs), _ = jax.lax.scan(tick, (carry, outputs),
                                   jnp.arange(total))
    # make the final outputs visible on every stage (callers usually
    # need the loss everywhere); sum works since other stages hold zeros
    return jax.lax.psum(outputs, axis_name)


def pipeline_apply_sharded(stage_fn, stacked_params, microbatches, mesh,
                           axis="pp"):
    """Jit pipeline_apply under shard_map over `axis`.

    stacked_params: pytree with leading dim n_stages == mesh.shape[axis].
    microbatches: (M, ...) replicated across stages.
    """
    from jax import shard_map

    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        assert leaf.shape[0] == n_stages, \
            f"stage dim {leaf.shape[0]} != mesh axis size {n_stages}"

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    fn = shard_map(
        lambda params, mb: pipeline_apply(stage_fn, params, mb, axis),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    stacked_params = jax.tree_util.tree_map(
        lambda p, spec: jax.device_put(p, NamedSharding(mesh, spec)),
        stacked_params, param_specs)
    microbatches = jax.device_put(microbatches, NamedSharding(mesh, P()))
    with mesh:
        return jax.jit(fn)(stacked_params, microbatches)
