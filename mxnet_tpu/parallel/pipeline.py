"""Pipeline parallelism over a 'pp' mesh axis: interleaved-GPipe forward
and a 1F1B training step.

New capability beyond the reference (SURVEY §2.4: its closest artifact is
a manual model-parallel LSTM recipe). Stage parameters are stacked on a
leading stage dimension and sharded over 'pp'; inside `shard_map` each
device runs its stage(s) and hands activations around a ring with
`ppermute` over ICI.

Two schedules:
  - `pipeline_apply` — interleaved GPipe (Megatron-style virtual stages):
    device s holds `num_virtual` chunks (virtual stage j*S + s is chunk j
    on device s), shrinking the fill/drain bubble from (S-1) ticks to
    (S-1)/v relative: efficiency M·v/(M·v + S - 1). Differentiable —
    jax.grad reverses the scan into the mirrored pipelined backward.
  - `pipeline_step_1f1b` — explicit one-forward-one-backward training
    step: forward inputs live in a ring buffer of depth S+1 and the
    backward RECOMPUTES the stage forward inside jax.vjp, so activation
    memory is O(S) per device instead of GPipe's O(M). Closed-form
    schedule: tau_f(m,s) = s+m (warmup m < S-s) else 2m+s;
    tau_b(m,s) = 2m + 2S - 1 - s; fwd and bwd land on opposite tick
    parities so each device runs at most one compute per tick.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["pipeline_apply", "pipeline_apply_sharded",
           "pipeline_step_1f1b", "pipeline_step_1f1b_sharded",
           "interleave_stages"]


def interleave_stages(params_list, n_stages):
    """Reorder a list of V = S*v per-virtual-stage param pytrees from
    natural order (virtual stage k) into the device-major stacking
    `pipeline_apply` expects (device s holds rows [s*v, (s+1)*v): chunk j
    of device s is virtual stage j*S + s)."""
    V = len(params_list)
    if V % n_stages:
        raise ValueError(f"{V} virtual stages not divisible by "
                         f"{n_stages} devices")
    v = V // n_stages
    order = [j * n_stages + s for s in range(n_stages) for j in range(v)]
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves),
        *[params_list[k] for k in order])


def pipeline_apply(stage_fn, stacked_params, microbatches, axis_name,
                   num_virtual=1):
    """Run inside shard_map/pmap over `axis_name`.

    stage_fn(params, x) -> y applies one (virtual) stage; stacked_params
    has a leading dim of num_virtual chunks per device (shard_map gives
    the local slice WITH the dim), stacked device-major — see
    `interleave_stages`. microbatches: (M, ...) replicated; with
    num_virtual > 1, M must divide into groups of S (the Megatron
    interleave contract). Returns (M, ...) outputs of the final virtual
    stage (psum-broadcast to every device).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage_id = jax.lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    v = num_virtual

    out_shape = jax.eval_shape(
        stage_fn, jax.tree_util.tree_map(lambda p: p[0], stacked_params),
        microbatches[0])
    if v > 1 and n_micro % n_stages:
        raise ValueError(f"interleaved schedule needs M % S == 0, got "
                         f"M={n_micro}, S={n_stages}")
    carry = jnp.zeros(out_shape.shape, out_shape.dtype)
    outputs = jnp.zeros((n_micro,) + out_shape.shape, out_shape.dtype)

    def tick(state, t):
        carry, outputs = state
        # schedule: device s's u-th unit (u = t - s) is chunk j of
        # microbatch m, processed group-by-group (groups of S microbatches)
        u = t - stage_id
        g = u // (v * n_stages)
        r = u % (v * n_stages)
        j = r // n_stages
        m = g * n_stages + (r % n_stages)
        valid = (u >= 0) & (u < v * n_micro) & (m < n_micro)
        mb_idx = jnp.clip(m, 0, n_micro - 1)
        # chunk 0 on device 0 eats fresh microbatches; everything else
        # eats the ring
        x = jnp.where((stage_id == 0) & (j == 0), microbatches[mb_idx],
                      carry)
        local = jax.tree_util.tree_map(
            lambda p: jax.lax.dynamic_index_in_dim(
                p, jnp.clip(j, 0, p.shape[0] - 1), keepdims=False),
            stacked_params)
        y = stage_fn(local, x)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        record = valid & (stage_id == n_stages - 1) & (j == v - 1)
        outputs = jax.lax.cond(
            record,
            lambda o: o.at[mb_idx].set(y),
            lambda o: o,
            outputs)
        # ring: stage s feeds s+1; the wrap S-1 -> 0 carries chunk
        # j -> j+1 activations back to device 0
        carry = jax.lax.ppermute(
            y, axis_name,
            [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return (carry, outputs), None

    total = v * n_micro + n_stages - 1
    # scan (not fori_loop) so the schedule is reverse-differentiable —
    # pipelined BACKWARD falls out of jax.grad through the same loop
    (_, outputs), _ = jax.lax.scan(tick, (carry, outputs),
                                   jnp.arange(total, dtype=jnp.int32))
    # make the final outputs visible on every stage (callers usually
    # need the loss everywhere); sum works since other stages hold zeros
    return jax.lax.psum(outputs, axis_name)


def pipeline_apply_sharded(stage_fn, stacked_params, microbatches, mesh,
                           axis="pp", num_virtual=1):
    """Jit pipeline_apply under shard_map over `axis`.

    stacked_params: pytree with leading dim S*num_virtual (device-major,
    see `interleave_stages`). microbatches: (M, ...) replicated across
    stages; with num_virtual > 1, M must be a multiple of S.
    """
    from .collectives import shard_map  # version-compat wrapper

    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        assert leaf.shape[0] == n_stages * num_virtual, \
            f"stage dim {leaf.shape[0]} != S*v = {n_stages * num_virtual}"
    if num_virtual > 1 and microbatches.shape[0] % n_stages:
        raise ValueError(
            f"interleaved schedule needs M % S == 0, got "
            f"M={microbatches.shape[0]}, S={n_stages}")

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    fn = shard_map(
        lambda params, mb: pipeline_apply(stage_fn, params, mb, axis,
                                          num_virtual=num_virtual),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    stacked_params = jax.tree_util.tree_map(
        lambda p, spec: jax.device_put(p, NamedSharding(mesh, spec)),
        stacked_params, param_specs)
    microbatches = jax.device_put(microbatches, NamedSharding(mesh, P()))
    with mesh:
        return jax.jit(fn)(stacked_params, microbatches)


def pipeline_step_1f1b(stage_fn, loss_fn, stacked_params, microbatches,
                       labels, axis_name):
    """One-forward-one-backward training step inside shard_map.

    stage_fn(params, x) -> y (homogeneous activation contract);
    loss_fn(y, label) -> scalar, applied on the last stage and MEANED over
    microbatches. Returns (loss_mean, local_param_grads).

    Memory: a depth-(S+1) ring buffer of stage INPUTS is the only saved
    state — the backward slot recomputes the stage forward inside jax.vjp
    (rematerialization: FLOPs for HBM, the TPU trade). In-flight
    microbatches per device never exceed S, so the buffer never aliases.
    Schedule (derivation in module docstring): fwd(m,s) at s+m (warmup)
    else 2m+s; bwd(m,s) at 2m+2S-1-s; opposite parities => one compute
    per device per tick; makespan 2(M+S-1).
    """
    S = jax.lax.psum(1, axis_name)
    s = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    local_params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)

    act = jax.eval_shape(stage_fn, local_params, microbatches[0])
    # S is concrete under shard_map (named axis sizes are static), so the
    # ring depth and permutation tables are compile-time constants
    depth = int(S) + 1

    def zeros_act():
        return jnp.zeros(act.shape, act.dtype)

    # two depth-(S+1) ring buffers: stage INPUTS saved for the recompute
    # backward, and RECEIVED activations awaiting their fwd slot (at the
    # warmup->steady boundary an activation waits up to S-s+1 ticks, so a
    # single carry register would be clobbered; the bwd hop is exactly
    # tick-aligned — tau_b(m,s) = tau_b(m,s+1)+1 — and needs no buffer)
    in_buf0 = jnp.zeros((depth,) + act.shape, act.dtype)
    rcv_buf0 = jnp.zeros((depth,) + act.shape, act.dtype)
    grads0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), local_params)

    def _fwd_sched(tau):
        """(microbatch, valid) this device forwards at tick tau."""
        warm = tau < S
        m_f = jnp.where(warm, tau - s, (tau - s) // 2)
        ok = jnp.where(warm,
                       (m_f >= 0) & (m_f < M),
                       ((tau - s) % 2 == 0) & (m_f >= S - s) & (m_f < M))
        return jnp.clip(m_f, 0, M - 1), ok

    def tick(state, tau):
        in_buf, rcv_buf, carry_bwd, grads, loss_sum, msg_in = state
        msg_y, msg_m, msg_ok = msg_in

        # bank the activation that arrived this tick (sender: stage s-1,
        # tick tau-1; the message carries its microbatch id)
        slot = msg_m % depth
        rcv_buf = rcv_buf.at[slot].set(
            jnp.where(msg_ok & (s > 0), msg_y, rcv_buf[slot]))

        mf_c, f_ok = _fwd_sched(tau)
        num = tau + s + 1 - 2 * S
        m_b = num // 2
        b_ok = (num % 2 == 0) & (m_b >= 0) & (m_b < M)
        mb_c = jnp.clip(m_b, 0, M - 1)
        x_in = jnp.where(s == 0, microbatches[mf_c],
                         rcv_buf[mf_c % depth])

        def do_fwd(in_buf, grads):
            y = stage_fn(local_params, x_in)
            in_buf = in_buf.at[mf_c % depth].set(x_in)
            return in_buf, grads, y, zeros_act(), jnp.float32(0.0)

        def do_bwd(in_buf, grads):
            x = in_buf[mb_c % depth]

            def f(p, xx):
                y = stage_fn(p, xx)
                return y, loss_fn(y, labels[mb_c])

            (y, l), vjp = jax.vjp(f, local_params, x)
            is_last = s == S - 1
            dy = jnp.where(is_last, jnp.zeros_like(carry_bwd), carry_bwd)
            dl = jnp.where(is_last, jnp.float32(1.0 / M), jnp.float32(0.0))
            dp, dx = vjp((dy.astype(y.dtype), dl.astype(l.dtype)))
            grads = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32), grads, dp)
            l_add = jnp.where(is_last, l.astype(jnp.float32) / M, 0.0)
            return in_buf, grads, zeros_act(), dx, l_add

        def idle(in_buf, grads):
            return (in_buf, grads, zeros_act(), zeros_act(),
                    jnp.float32(0.0))

        in_buf, grads, y_send, dx_send, l_add = jax.lax.cond(
            f_ok, do_fwd,
            lambda b, g: jax.lax.cond(b_ok, do_bwd, idle, b, g),
            in_buf, grads)

        loss_sum = loss_sum + l_add
        fwd_ring = [(i, (i + 1) % S) for i in range(S)]
        msg = (jax.lax.ppermute(y_send, axis_name, fwd_ring),
               jax.lax.ppermute(mf_c, axis_name, fwd_ring),
               jax.lax.ppermute(f_ok, axis_name, fwd_ring))
        carry_bwd = jax.lax.ppermute(
            dx_send, axis_name, [((i + 1) % S, i) for i in range(S)])
        return (in_buf, rcv_buf, carry_bwd, grads, loss_sum, msg), None

    total = 2 * (M + S - 1)
    state0 = (in_buf0, rcv_buf0, zeros_act(), grads0, jnp.float32(0.0),
              (zeros_act(), jnp.int32(0), jnp.bool_(False)))
    (_, _, _, grads, loss_sum, _), _ = jax.lax.scan(
        tick, state0, jnp.arange(total, dtype=jnp.int32))
    loss = jax.lax.psum(loss_sum, axis_name)  # only last stage added
    return loss, grads


def pipeline_step_1f1b_sharded(stage_fn, loss_fn, stacked_params,
                               microbatches, labels, mesh, axis="pp"):
    """Jit pipeline_step_1f1b over `axis`; returns (loss, stacked_grads)
    with grads sharded like the params."""
    from .collectives import shard_map  # version-compat wrapper

    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        assert leaf.shape[0] == n_stages

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    grad_specs = param_specs

    def run(params, mb, lb):
        loss, g = pipeline_step_1f1b(stage_fn, loss_fn, params, mb, lb,
                                     axis)
        # re-add the local stage dim so out_specs can shard it
        g = jax.tree_util.tree_map(lambda a: a[None], g)
        return loss, g

    fn = shard_map(run, mesh=mesh,
                   in_specs=(param_specs, P(), P()),
                   out_specs=(P(), grad_specs),
                   check_vma=False)
    stacked_params = jax.tree_util.tree_map(
        lambda p, spec: jax.device_put(p, NamedSharding(mesh, spec)),
        stacked_params, param_specs)
    microbatches = jax.device_put(microbatches, NamedSharding(mesh, P()))
    labels = jax.device_put(labels, NamedSharding(mesh, P()))
    with mesh:
        return jax.jit(fn)(stacked_params, microbatches, labels)
