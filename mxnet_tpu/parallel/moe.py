"""Expert parallelism: Mixture-of-Experts routing over an 'ep' mesh axis.

New capability beyond the reference (SURVEY §2.4: the reference has only
data parallelism). GShard-style top-k token routing: a router scores
tokens, dispatch/combine tensors route them to per-expert FFNs, and the
expert dimension is sharded over the mesh's 'ep' axis — XLA lowers the
dispatch einsums into all-to-alls over ICI.

The math follows the public GShard/Switch formulation (top-k gating with
capacity and auxiliary load-balancing loss); the implementation is dense
einsum routing, the layout XLA maps best onto the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_jit_cache = {}

__all__ = ["top_k_routing", "moe_ffn", "moe_ffn_sharded", "init_moe_params"]


def top_k_routing(router_logits, num_experts, capacity, top_k=2):
    """Compute dispatch/combine tensors from router logits.

    router_logits: (T, E) for T tokens. Returns
      dispatch (T, E, C) one-hot routing, combine (T, E, C) gate-weighted,
      aux_loss (scalar load-balancing loss, Switch-style).
    """
    T = router_logits.shape[0]
    probs = jax.nn.softmax(router_logits, axis=-1)           # (T, E)
    # top-k expert choices per token
    gate_vals, expert_idx = jax.lax.top_k(probs, top_k)      # (T, k)
    # position of each token within its expert's capacity buffer:
    # cumulative count of earlier tokens choosing the same expert
    onehot = jax.nn.one_hot(expert_idx, num_experts,
                            dtype=jnp.int32)                 # (T, k, E)
    # order: iterate k slots major so primary choices claim slots first
    flat = onehot.transpose(1, 0, 2).reshape(top_k * T, num_experts)
    pos_flat = jnp.cumsum(flat, axis=0) - flat               # (k*T, E)
    pos = pos_flat.reshape(top_k, T, num_experts).transpose(1, 0, 2)
    slot = jnp.sum(pos * onehot, axis=-1)                    # (T, k)
    keep = slot < capacity
    gate_vals = gate_vals * keep
    # renormalize kept gates per token
    denom = jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)
    gate_vals = gate_vals / denom
    slot_oh = jax.nn.one_hot(jnp.where(keep, slot, capacity),
                             capacity + 1,
                             dtype=router_logits.dtype)[..., :capacity]
    exp_oh = jax.nn.one_hot(expert_idx, num_experts,
                            dtype=router_logits.dtype)       # (T, k, E)
    dispatch = jnp.einsum("tke,tkc->tec", exp_oh,
                          slot_oh * keep[..., None])
    combine = jnp.einsum("tke,tkc->tec", exp_oh,
                         slot_oh * gate_vals[..., None])
    # Switch aux loss: E * sum_e fraction_tokens_e * mean_prob_e
    primary = jax.nn.one_hot(expert_idx[:, 0], num_experts,
                             dtype=probs.dtype)
    frac = primary.mean(0)
    aux = num_experts * jnp.sum(frac * probs.mean(0))
    return dispatch, combine, aux


def init_moe_params(key, d_model, d_hidden, num_experts, dtype=jnp.float32):
    """Router + per-expert FFN weights (E stacked for ep sharding)."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / jnp.sqrt(d_model)
    scale_out = 1.0 / jnp.sqrt(d_hidden)
    return {
        "router": jax.random.normal(k1, (d_model, num_experts),
                                    dtype) * scale_in,
        "wi": jax.random.normal(k2, (num_experts, d_model, d_hidden),
                                dtype) * scale_in,
        "wo": jax.random.normal(k3, (num_experts, d_hidden, d_model),
                                dtype) * scale_out,
    }


def moe_ffn(params, x, capacity_factor=1.25, top_k=2):
    """MoE FFN over tokens x (T, D). Returns (out (T, D), aux_loss)."""
    T, D = x.shape
    E = params["router"].shape[1]
    capacity = max(1, int(capacity_factor * T * top_k / E))
    logits = x @ params["router"]
    dispatch, combine, aux = top_k_routing(logits, E, capacity, top_k)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)       # (E, C, D)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in,
                               params["wi"]))
    expert_out = jnp.einsum("ech,ehd->ecd", h, params["wo"])
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out, aux


def moe_ffn_sharded(params, x, mesh, axis="ep", capacity_factor=1.25,
                    top_k=2):
    """jit moe_ffn with the expert dimension sharded over `axis`; XLA
    inserts the token all-to-alls around the expert matmuls."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    ep = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    params = {
        "router": jax.device_put(params["router"], repl),
        "wi": jax.device_put(params["wi"], ep),
        "wo": jax.device_put(params["wo"], ep),
    }
    x = jax.device_put(x, repl)

    key = (mesh, axis, capacity_factor, top_k)
    run = _jit_cache.get(key)
    if run is None:
        @jax.jit
        def run(p, xx):
            out, aux = moe_ffn(p, xx, capacity_factor, top_k)
            return out, aux

        _jit_cache[key] = run

    with mesh:
        return run(params, x)
