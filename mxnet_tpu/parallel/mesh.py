"""Device-mesh construction.

The TPU scaling recipe (scaling-book): pick a mesh whose inner axes map to
ICI-adjacent chips, annotate shardings, let XLA insert collectives. Multi-host
is transparent: jax.devices() spans the slice once jax.distributed is
initialized (the tools/launch.py analog).
"""
from __future__ import annotations

import numpy as _np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "Mesh", "NamedSharding", "PartitionSpec",
           "data_parallel_mesh", "local_mesh"]


def make_mesh(axes, devices=None):
    """Build a Mesh from {axis_name: size}; size -1 infers the remainder.

    Example: make_mesh({'dp': -1, 'tp': 4}) on 32 chips -> 8x4 mesh.
    Axis order puts the *last* axis innermost (fastest-varying), which on TPU
    means adjacent chips — put tp/sp axes last so their collectives ride
    nearest-neighbor ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    names = list(axes.keys())
    sizes = list(axes.values())
    unknown = [i for i, s in enumerate(sizes) if s in (-1, None)]
    known = 1
    for s in sizes:
        if s not in (-1, None):
            known *= s
    if unknown:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[unknown[0]] = n // known
        for i in unknown[1:]:
            sizes[i] = 1
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} != {n} devices")
    arr = _np.array(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(devices=None):
    """1-D 'dp' mesh over all devices (the kvstore='tpu_dist' topology)."""
    return make_mesh({"dp": -1}, devices)


def local_mesh(axes=None):
    """Mesh over this process's local devices only."""
    return make_mesh(axes or {"dp": -1}, jax.local_devices())
