"""Device-mesh construction.

The TPU scaling recipe (scaling-book): pick a mesh whose inner axes map to
ICI-adjacent chips, annotate shardings, let XLA insert collectives. Multi-host
is transparent: jax.devices() spans the slice once jax.distributed is
initialized (the tools/launch.py analog).
"""
from __future__ import annotations

import numpy as _np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "Mesh", "NamedSharding", "PartitionSpec",
           "ShardingError", "data_parallel_mesh", "local_mesh"]


class ShardingError(ValueError):
    """A sharding request that cannot be laid out: a mesh spec that does
    not match the device count, or a parameter dimension that is not
    divisible by the mesh axis its PartitionSpec assigns it to. Raised
    eagerly with the param name and spec in the message, instead of
    letting jax fail later with an opaque shape error. Defined here (not
    in mxnet_tpu.sharding) so mesh-level helpers can raise it without a
    circular import; the sharding package re-exports it."""


def make_mesh(axes, devices=None):
    """Build a Mesh from {axis_name: size}; size -1 infers the remainder.

    Example: make_mesh({'dp': -1, 'tp': 4}) on 32 chips -> 8x4 mesh.
    Axis order puts the *last* axis innermost (fastest-varying), which on TPU
    means adjacent chips — put tp/sp axes last so their collectives ride
    nearest-neighbor ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    names = list(axes.keys())
    sizes = list(axes.values())
    unknown = [i for i, s in enumerate(sizes) if s in (-1, None)]
    known = 1
    for s in sizes:
        if s not in (-1, None):
            known *= s
    if unknown:
        if n % known:
            raise ShardingError(
                f"{n} devices not divisible by {known} "
                f"(mesh spec {dict(zip(names, axes.values()))})")
        sizes[unknown[0]] = n // known
        for i in unknown[1:]:
            sizes[i] = 1
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise ShardingError(
            f"mesh {dict(zip(names, sizes))} != {n} devices")
    arr = _np.array(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(devices=None):
    """1-D 'dp' mesh over all devices (the kvstore='tpu_dist' topology)."""
    return make_mesh({"dp": -1}, devices)


def local_mesh(axes=None):
    """Mesh over this process's local devices only."""
    return make_mesh(axes or {"dp": -1}, jax.local_devices())


def _check_divisible(name, shape, spec, mesh):
    """Raise ShardingError naming the param and spec when a sharded
    dimension is not divisible by the product of its mesh axes — the
    eager, readable version of the shape error jax would raise deep
    inside device_put/lowering."""
    for d, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        factor = 1
        for ax in axes:
            if ax not in mesh.shape:
                raise ShardingError(
                    f"parameter {name}: spec {spec} names mesh axis "
                    f"{ax!r}, but the mesh has axes "
                    f"{tuple(mesh.axis_names)}")
            factor *= mesh.shape[ax]
        if d >= len(shape) or shape[d] % factor:
            dim = shape[d] if d < len(shape) else "<missing>"
            raise ShardingError(
                f"parameter {name} with shape {tuple(shape)}: dim {d} "
                f"({dim}) is not divisible by mesh "
                f"axis {'x'.join(axes)} (size {factor}) in spec {spec}")


def shard_params(params, mesh, spec_fn=None):
    """Lay Gluon Parameters (dict name->Parameter) out on a device mesh.

    Default: replicated (pure data parallelism). `spec_fn(name, shape)` may
    return a PartitionSpec to tensor-shard individual params. Grad buffers
    follow their parameter's sharding. This is the user-level mesh entry of
    the kvstore='tpu_dist' path: after this, eager ops and CachedOp jits
    compute with GSPMD semantics and XLA inserts the gradient all-reduce
    during backward (subsuming the reference's push/pull round trip).

    `mesh` may be a built Mesh or an axes spec ({'dp': -1} / (('dp', -1),))
    — specs go through :func:`make_mesh`, so -1 sizes infer from the
    device count. A spec that shards a dimension not divisible by its
    mesh axis raises :class:`ShardingError` naming the param and spec.
    """
    if not isinstance(mesh, Mesh):
        mesh = make_mesh(dict(mesh))
    for name, p in params.items():
        if p._data_map is None:
            raise ValueError(f"parameter {name} is not initialized")
        spec = spec_fn(name, p.shape) if spec_fn is not None else None
        if spec is None:
            spec = PartitionSpec()
        _check_divisible(name, p.shape, spec, mesh)
        sh = NamedSharding(mesh, spec)
        for arr in p._data_map.values():
            arr._data = jax.device_put(arr._data, sh)
            arr._version += 1
            if arr._grad is not None:
                arr._grad._data = jax.device_put(arr._grad._data, sh)
                arr._grad._version += 1
    return mesh


def shard_batch(x, mesh, axis="dp"):
    """Shard an input batch over a mesh axis (leading dim). Accepts NDArray
    or raw array; returns the same kind."""
    spec = PartitionSpec(axis)
    sh = NamedSharding(mesh, spec)
    from ..ndarray.ndarray import NDArray

    if isinstance(x, NDArray):
        x._data = jax.device_put(x._data, sh)
        x._version += 1
        return x
    return jax.device_put(x, sh)
