"""Parallelism over device meshes — the SPMD core.

The reference's only strategy is data parallelism via kvstore (SURVEY.md
§2.4); this package provides DP at parity *plus* the sharding axes the
reference lacks (TP/SP), expressed the TPU-native way: a `jax.sharding.Mesh`
with named axes, sharding specs on params/activations, and XLA-inserted
collectives over ICI.

Modules:
  mesh        — mesh construction helpers (dp/tp/sp axes, multi-host aware)
  collectives — psum/all_gather/reduce_scatter/ppermute wrappers
  data_parallel — sharded training step builder (grad psum over 'dp')
  ring_attention — K/V-streaming sequence parallelism (ICI ring)
  ulysses     — all-to-all head↔sequence parallelism (DeepSpeed-Ulysses)
"""
from . import collectives, mesh, moe, pipeline, ring_attention, ulysses  # noqa: F401
from .data_parallel import make_data_parallel_step  # noqa: F401
from .mesh import (ShardingError, make_mesh, shard_batch,  # noqa: F401
                   shard_params)
from .ring_attention import (  # noqa: F401
    ring_attention_sharded,
    ring_flash_attention_sharded,
)
from .moe import moe_ffn_sharded  # noqa: F401
from .pipeline import (interleave_stages, pipeline_apply_sharded,  # noqa: F401
                       pipeline_step_1f1b_sharded)
from .ulysses import ulysses_attention_sharded  # noqa: F401
