"""Ring attention — sequence/context parallelism over the ICI ring.

A capability the reference lacks entirely (SURVEY.md §5 "Long-context /
sequence parallelism — absent"), built TPU-first: the sequence axis is
sharded over a mesh axis; each device holds a Q/K/V shard and K/V blocks
rotate around the ring via lax.ppermute while a numerically-stable streaming
softmax (online max/denominator) accumulates the output. Compute on each hop
overlaps the neighbor exchange (XLA schedules ppermute async), so the
attention cost is flat in the number of devices while max sequence length
scales linearly with them.

References (public): Liu et al., "Ring Attention with Blockwise
Transformers" (2023); the streaming-softmax recurrence is the
FlashAttention online-softmax.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_sharded"]


def _stable_block(q, k, v, o, m, l, scale, mask=None):
    """One blockwise-attention accumulation step (online softmax)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    # guard -inf rows (fully masked block): exp(-inf - -inf) -> use where
    p = jnp.exp(s - jnp.where(jnp.isneginf(m_new), 0.0, m_new))
    corr = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m)
                   - jnp.where(jnp.isneginf(m_new), 0.0, m_new))
    corr = jnp.where(jnp.isneginf(m), 0.0, corr)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Per-device body: full attention over a sequence sharded on
    `axis_name`. Call inside shard_map/pjit; q,k,v are local shards
    (batch, heads, seq_local, head_dim)."""
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5

    o = jnp.zeros_like(q, dtype=jnp.float32)
    m = jnp.full(q.shape[:3] + (1,), -jnp.inf, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)  # noqa: E741

    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def accum(i, o, m, l, k_blk, v_blk):  # noqa: E741
        src = (my - i) % n  # which device's K/V block we now hold
        if causal:
            q_idx = my * s_local + jnp.arange(s_local)[:, None]
            k_idx = src * s_local + jnp.arange(k_blk.shape[2])[None, :]
            mask = (q_idx >= k_idx)[None, None]
        else:
            mask = None
        return _stable_block(
            qf, k_blk.astype(jnp.float32), v_blk.astype(jnp.float32),
            o, m, l, scale, mask)

    def body(i, carry):
        o, m, l, k_blk, v_blk = carry  # noqa: E741
        o, m, l = accum(i, o, m, l, k_blk, v_blk)  # noqa: E741
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_blk, v_blk

    # n-1 hops with permute; the final block accumulates outside the loop
    # so the ring doesn't pay a wasted last-iteration ppermute pair
    o, m, l, k_last, v_last = jax.lax.fori_loop(  # noqa: E741
        0, n - 1, body, (o, m, l, k, v))
    o, m, l = accum(n - 1, o, m, l, k_last, v_last)  # noqa: E741
    out = o / jnp.where(l == 0, 1.0, l)
    return out.astype(q.dtype)


_jit_cache = {}


def ring_attention_sharded(q, k, v, mesh, axis="sp", causal=False,
                           scale=None):
    """Convenience wrapper: shard (b, h, S, d) arrays on the sequence dim
    over `axis` and run ring attention as one jitted shard_map program.
    The jitted program is cached per (mesh, axis, causal, scale) so training
    loops hit the compile cache."""
    from jax import shard_map

    key = (mesh, axis, causal, scale)
    run = _jit_cache.get(key)
    if run is None:
        spec = P(None, None, axis, None)

        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=spec, check_vma=False)
        def body(ql, kl, vl):
            return ring_attention(ql, kl, vl, axis, causal=causal,
                                  scale=scale)

        run = jax.jit(body)
        _jit_cache[key] = run
    return run(q, k, v)
