"""Ring attention — sequence/context parallelism over the ICI ring.

A capability the reference lacks entirely (SURVEY.md §5 "Long-context /
sequence parallelism — absent"), built TPU-first: the sequence axis is
sharded over a mesh axis; each device holds a Q/K/V shard and K/V blocks
rotate around the ring via lax.ppermute while a numerically-stable streaming
softmax (online max/denominator) accumulates the output. Compute on each hop
overlaps the neighbor exchange (XLA schedules ppermute async), so the
attention cost is flat in the number of devices while max sequence length
scales linearly with them.

References (public): Liu et al., "Ring Attention with Blockwise
Transformers" (2023); the streaming-softmax recurrence is the
FlashAttention online-softmax.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import collectives as _collectives

__all__ = ["ring_attention", "ring_attention_sharded",
           "ring_flash_attention", "ring_flash_attention_sharded"]


def _stable_block(q, k, v, o, m, l, scale, mask=None):
    """One blockwise-attention accumulation step (online softmax)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    # guard -inf rows (fully masked block): exp(-inf - -inf) -> use where
    p = jnp.exp(s - jnp.where(jnp.isneginf(m_new), 0.0, m_new))
    corr = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m)
                   - jnp.where(jnp.isneginf(m_new), 0.0, m_new))
    corr = jnp.where(jnp.isneginf(m), 0.0, corr)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Per-device body: full attention over a sequence sharded on
    `axis_name`. Call inside shard_map/pjit; q,k,v are local shards
    (batch, heads, seq_local, head_dim)."""
    n = _collectives.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5

    o = jnp.zeros_like(q, dtype=jnp.float32)
    m = jnp.full(q.shape[:3] + (1,), -jnp.inf, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)  # noqa: E741

    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def accum(i, o, m, l, k_blk, v_blk):  # noqa: E741
        src = (my - i) % n  # which device's K/V block we now hold
        if causal:
            q_idx = my * s_local + jnp.arange(s_local)[:, None]
            k_idx = src * s_local + jnp.arange(k_blk.shape[2])[None, :]
            mask = (q_idx >= k_idx)[None, None]
        else:
            mask = None
        return _stable_block(
            qf, k_blk.astype(jnp.float32), v_blk.astype(jnp.float32),
            o, m, l, scale, mask)

    def body(i, carry):
        o, m, l, k_blk, v_blk = carry  # noqa: E741
        o, m, l = accum(i, o, m, l, k_blk, v_blk)  # noqa: E741
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_blk, v_blk

    # n-1 hops with permute; the final block accumulates outside the loop
    # so the ring doesn't pay a wasted last-iteration ppermute pair
    o, m, l, k_last, v_last = jax.lax.fori_loop(  # noqa: E741
        0, n - 1, body, (o, m, l, k, v))
    o, m, l = accum(n - 1, o, m, l, k_last, v_last)  # noqa: E741
    out = o / jnp.where(l == 0, 1.0, l)
    return out.astype(q.dtype)


_jit_cache = {}


def ring_attention_sharded(q, k, v, mesh, axis="sp", causal=False,
                           scale=None):
    """Convenience wrapper: shard (b, h, S, d) arrays on the sequence dim
    over `axis` and run ring attention as one jitted shard_map program.
    The jitted program is cached per (mesh, axis, causal, scale) so training
    loops hit the compile cache."""
    from .collectives import shard_map  # version-compat wrapper

    key = (mesh, axis, causal, scale)
    run = _jit_cache.get(key)
    if run is None:
        spec = P(None, None, axis, None)

        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=spec, check_vma=False)
        def body(ql, kl, vl):
            return ring_attention(ql, kl, vl, axis, causal=causal,
                                  scale=scale)

        run = jax.jit(body)
        _jit_cache[key] = run
    return run(q, k, v)


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, interpret,
                         valid_len=None):
    """Ring attention with the Pallas flash kernel as the per-hop block
    compute. Each hop runs the O(S_local)-memory fused kernel on the
    resident K/V block and merges normalized partials exactly via their
    logsumexp:

        lse = logaddexp(lse_a, lse_b)
        out = exp(lse_a - lse) * out_a + exp(lse_b - lse) * out_b

    Causal mode: hops from future devices contribute lse = -inf (skipped
    by the merge); the diagonal hop runs the causal kernel under lax.cond.
    Same contract as `ring_attention` (call inside shard_map; q/k/v are
    (B, H, S_local, D) shards).
    """
    import jax as _jax

    from ..ops.pallas_attention import _flash_fwd

    n = _collectives.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    bq = min(128, s_local)
    bk = min(128, s_local)

    out = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(out_a, lse_a, out_b, lse_b):
        lse_new = jnp.logaddexp(lse_a, lse_b)
        safe = jnp.where(jnp.isneginf(lse_new), 0.0, lse_new)
        w_a = jnp.where(jnp.isneginf(lse_a), 0.0,
                        jnp.exp(lse_a - safe))[..., None]
        w_b = jnp.where(jnp.isneginf(lse_b), 0.0,
                        jnp.exp(lse_b - safe))[..., None]
        return w_a * out_a + w_b * out_b, lse_new

    def hop(i, out, lse, k_blk, v_blk):
        src = (my - i) % n
        if causal:
            def _skip():
                # future keys: no kernel launch, zero contribution
                return (jnp.zeros(q.shape, q.dtype),
                        jnp.full((q.shape[0] * q.shape[1], q.shape[2]),
                                 -jnp.inf, jnp.float32))

            blk_out, blk_lse = _jax.lax.cond(
                src > my,
                _skip,
                lambda: _jax.lax.cond(
                    src == my,
                    lambda: _flash_fwd(q, k_blk, v_blk, True, scale,
                                       bq, bk, interpret, valid_len),
                    lambda: _flash_fwd(q, k_blk, v_blk, False, scale,
                                       bq, bk, interpret, valid_len)),
            )
        else:
            blk_out, blk_lse = _flash_fwd(q, k_blk, v_blk, False, scale,
                                          bq, bk, interpret, valid_len)
        blk_lse = blk_lse.reshape(q.shape[:3])
        return merge(out, lse, blk_out.astype(jnp.float32), blk_lse)

    def body(i, carry):
        out, lse, k_blk, v_blk = carry
        out, lse = hop(i, out, lse, k_blk, v_blk)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return out, lse, k_blk, v_blk

    out, lse, k_last, v_last = jax.lax.fori_loop(
        0, n - 1, body, (out, lse, k, v))
    out, lse = hop(n - 1, out, lse, k_last, v_last)
    return out.astype(q.dtype), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, scale, interpret,
                valid_len=None):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                  interpret, valid_len)
    return out


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, scale, interpret,
                        valid_len=None):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                    interpret, valid_len)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, scale, interpret, valid_len,
                        res, g):
    """Ring backward: one full rotation; each hop runs the block-streamed
    Pallas flash backward (_flash_bwd) between the local Q and the
    resident K/V block using the saved GLOBAL lse, so memory stays
    O(S_local) — no (S_local, S_local) score matrix. Each hop's dK/dV is
    carried around the ring back to the block's owner; dQ accumulates
    locally. Cross-hop causal structure maps onto the kernel's flag:
    past hops run it un-causal, the diagonal hop causal, future hops are
    skipped entirely."""
    from ..ops.pallas_attention import _flash_bwd

    q, k, v, out, lse = res
    n = _collectives.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    bq = min(128, s_local)
    bk = min(128, s_local)
    perm = [(i, (i + 1) % n) for i in range(n)]

    b, h = q.shape[0], q.shape[1]
    lse_flat = lse.reshape(b * h, s_local)  # _flash_bwd's (bh, S) layout

    def grads_for(k_blk, v_blk, is_causal):
        return _flash_bwd(q, k_blk, v_blk, out, lse_flat, g, is_causal,
                          scale, bq, bk, interpret, valid_len)

    def body(i, carry):
        dq, k_blk, v_blk, dk, dv = carry
        src = (my - i) % n
        if causal:
            def _skip():
                return (jnp.zeros(q.shape, q.dtype),
                        jnp.zeros(k.shape, k.dtype),
                        jnp.zeros(v.shape, v.dtype))

            dq_h, dk_blk, dv_blk = jax.lax.cond(
                src > my,
                _skip,
                lambda: jax.lax.cond(
                    src == my,
                    lambda: grads_for(k_blk, v_blk, True),
                    lambda: grads_for(k_blk, v_blk, False)),
            )
        else:
            dq_h, dk_blk, dv_blk = grads_for(k_blk, v_blk, False)
        dq = dq + dq_h.astype(jnp.float32)
        # rotate the K/V blocks AND their accumulated grads together so
        # every block's dK/dV arrives home after the full cycle
        dk = jax.lax.ppermute(dk + dk_blk.astype(jnp.float32),
                              axis_name, perm)
        dv = jax.lax.ppermute(dv + dv_blk.astype(jnp.float32),
                              axis_name, perm)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return dq, k_blk, v_blk, dk, dv

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, _, _, dk, dv = jax.lax.fori_loop(
        0, n, body, (dq0, k, v, jnp.zeros(k.shape, jnp.float32),
                     jnp.zeros(v.shape, jnp.float32)))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_flash_attention(q, k, v, axis_name, causal=False, scale=None,
                         interpret=None):
    """Ring attention with the Pallas flash kernel per forward hop and a
    blockwise ring backward (custom_vjp) — trainable end to end. See
    _ring_flash_fwd_impl for the forward schedule and _ring_flash_vjp_bwd
    for the gradient rotation."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        # axon is the tunneled TPU platform — kernel-capable, like
        # ops/pallas_attention.flash_attention's check
        interpret = jax.default_backend() not in ("tpu", "axon")
    if q.shape[-1] % 8:
        # ragged head dim: blocks can't stay lane-aligned
        return ring_attention(q, k, v, axis_name, causal=causal,
                              scale=scale)
    from ..ops.pallas_attention import _tile_pad_len

    s_local = q.shape[2]
    s_pad = _tile_pad_len(s_local, 128)
    if s_pad == s_local:
        return _ring_flash(q, k, v, axis_name, causal, scale, interpret)
    # Ragged local shard: tile-pad; the kernel masks padded keys of every
    # hop's resident block via the static valid_len (padding sits at the
    # tail of each device's block, so hop-granular causality is unchanged).
    pad = [(0, 0), (0, 0), (0, s_pad - s_local), (0, 0)]
    out = _ring_flash(jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad),
                      axis_name, causal, scale, interpret, s_local)
    return out[:, :, :s_local]


def ring_flash_attention_sharded(q, k, v, mesh, axis="sp", causal=False,
                                 scale=None, interpret=None):
    """shard_map wrapper: sequence axis sharded over `axis`, flash kernel
    per hop (the production long-context path on TPU). Jitted program
    cached per (mesh, axis, causal, scale, interpret) like
    ring_attention_sharded."""
    from .collectives import shard_map  # version-compat wrapper

    key = ("flash", mesh, axis, causal, scale, interpret)
    run = _jit_cache.get(key)
    if run is None:
        spec = P(None, None, axis, None)

        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=spec, check_vma=False)
        def body(ql, kl, vl):
            return ring_flash_attention(ql, kl, vl, axis, causal=causal,
                                        scale=scale, interpret=interpret)

        run = jax.jit(body)
        _jit_cache[key] = run
    return run(q, k, v)
