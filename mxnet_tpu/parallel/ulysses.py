"""Ulysses-style sequence parallelism: all-to-all head↔sequence resharding.

The second long-context strategy next to ring_attention (SURVEY.md §5 —
absent in the reference, green-field here). Where ring attention streams
K/V blocks around the ICI ring, Ulysses keeps attention *local*: activations
arrive sharded on the sequence axis, an all-to-all reshards them to
head-sharded/full-sequence, each device runs plain attention over its head
group (one big MXU matmul chain — no streaming softmax), and a second
all-to-all restores sequence sharding.

Cost model (scaling-book): 2 all-to-alls of the qkv/out tensors vs ring's
(n-1) K/V ppermute hops — all-to-all rides ICI at full bisection bandwidth,
so Ulysses wins when heads >= devices and sequence lengths are moderate;
ring wins for extreme sequence lengths (memory: Ulysses materializes full-S
scores per head group).

Reference (public): Jacobs et al., "DeepSpeed Ulysses" (2023).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import collectives as _collectives

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None):
    """Per-device body (call inside shard_map): q/k/v are
    (batch, heads, seq_local, head_dim) shards on the sequence axis;
    heads must divide the axis size evenly.
    """
    n = _collectives.axis_size(axis_name)
    b, h, s_local, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by axis size {n}")
    if scale is None:
        scale = d ** -0.5

    def seq_to_heads(x):
        # (b, h, s_loc, d) -> all-to-all -> (b, h/n, S, d): split heads
        # into n peer groups; the exchange removes the split axis and
        # inserts the received peer axis at concat_axis, giving
        # (b, h/n, n, s_loc, d) whose flatten is the full ordered sequence
        x = x.reshape(b, n, h // n, s_local, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                               tiled=False)
        return x.reshape(b, h // n, n * s_local, d)

    def heads_to_seq(x):
        # inverse: (b, h/n, S, d) -> (b, h, s_local, d)
        x = x.reshape(b, h // n, n, s_local, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                               tiled=False)
        return x.reshape(b, h, s_local, d)

    qh = seq_to_heads(q.astype(jnp.float32))
    kh = seq_to_heads(k.astype(jnp.float32))
    vh = seq_to_heads(v.astype(jnp.float32))

    s_full = qh.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        idx = jnp.arange(s_full)
        scores = jnp.where(idx[None, None, :, None] >= idx[None, None,
                                                          None, :],
                           scores, -jnp.inf)
    att = jax.nn.softmax(scores, axis=-1)
    oh = jnp.einsum("bhqk,bhkd->bhqd", att, vh)
    return heads_to_seq(oh).astype(q.dtype)


_jit_cache = {}


def ulysses_attention_sharded(q, k, v, mesh, axis="sp", causal=False,
                              scale=None):
    """Convenience wrapper mirroring ring_attention_sharded: (b, h, S, d)
    arrays sharded on the sequence dim over `axis`; one jitted shard_map
    program cached per (mesh, axis, causal, scale)."""
    from .collectives import shard_map  # version-compat wrapper

    key = (mesh, axis, causal, scale)
    run = _jit_cache.get(key)
    if run is None:
        spec = P(None, None, axis, None)

        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=spec, check_vma=False)
        def body(ql, kl, vl):
            return ulysses_attention(ql, kl, vl, axis, causal=causal,
                                     scale=scale)

        run = jax.jit(body)
        _jit_cache[key] = run
    return run(q, k, v)
