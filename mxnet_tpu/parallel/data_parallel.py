"""Sharded training-step builders.

Two styles, both idiomatic on TPU:

  * GSPMD (default): params replicated / batch sharded over 'dp'; one jit
    with sharding annotations — XLA's SPMD partitioner inserts the gradient
    all-reduce and overlaps it with backprop. This subsumes the reference's
    P3 priority-based push/pull overlap (src/kvstore/p3store_dist.h) —
    the latency-hiding scheduler does it per-HLO instead of per-layer.

  * explicit shard_map: per-device code with explicit lax.psum — useful when
    composing with tensor/sequence parallel inner collectives.
"""
from __future__ import annotations

from functools import partial

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["make_data_parallel_step", "make_shard_map_step"]


def make_data_parallel_step(loss_fn, update_fn, mesh, axis="dp",
                            param_specs=None, donate=True):
    """Build `step(params, opt_state, batch, lr) -> (params, opt_state, loss)`.

    loss_fn(params, batch) -> scalar; update_fn(params, grads, opt_state, lr)
    -> (new_params, new_opt_state). Batch is sharded over `axis` (leading
    dim); params replicated unless `param_specs` (a PartitionSpec pytree
    prefix) shards them (tensor parallelism).
    """
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(axis))
    if param_specs is None:
        param_sh = repl
    else:
        param_sh = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec), param_specs,
            is_leaf=lambda x: isinstance(x, P))

    def step(params, opt_state, batch, lr):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_params, new_opt = update_fn(params, grads, opt_state, lr)
        return new_params, new_opt, loss

    kwargs = {}
    if donate:
        kwargs["donate_argnums"] = (0, 1)
    return jax.jit(
        step,
        in_shardings=(param_sh, param_sh, batch_sh, None),
        out_shardings=(param_sh, param_sh, repl),
        **kwargs,
    )


def make_shard_map_step(loss_fn, update_fn, mesh, axis="dp"):
    """Explicit-collective variant: per-device bodies + lax.psum on grads."""
    from .collectives import shard_map  # version-compat wrapper

    # check_vma=False: jax's replication checker rewrites grads of
    # replicated (P()) inputs with an extra psum, inflating them by the
    # axis size; with it off we own the collectives (explicit pmean).
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def body(params, opt_state, batch, lr):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = jax.lax.pmean(grads, axis)
        loss = jax.lax.pmean(loss, axis)
        new_params, new_opt = update_fn(params, grads, opt_state, lr)
        return new_params, new_opt, loss

    return jax.jit(body, donate_argnums=(0, 1))
