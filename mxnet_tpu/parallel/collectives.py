"""Collective wrappers over XLA (psum/all_gather/reduce_scatter/ppermute).

These replace the reference's entire comm layer: CommCPU/CommDevice reduce
(src/kvstore/comm.h), tree allreduce (comm_tree.h), NCCL (kvstore_nccl.h) and
ps-lite push/pull — all become XLA collectives that ride ICI within a slice
and DCN across slices, scheduled asynchronously by the compiler.

Every wrapper records call count / input bytes / dispatch wall-time into
the telemetry registry (`collective_*` counters labeled by op — see
docs/telemetry.md). Dispatch time, not completion: the returned arrays are
async like everything else on the device stream.
"""
from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: experimental spelling, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma,
                              **kw)

from ..diagnostics import spans as _spans
from ..diagnostics import watchdog as _watchdog
from ..telemetry import instruments as _telemetry

__all__ = ["psum_tree", "psum_tree_flat", "psum_tree_flat_traced",
           "allreduce_mean", "all_gather", "reduce_scatter",
           "ring_permute", "axis_size"]


def axis_size(axis_name):
    """Static size of a named mesh axis inside shard_map (version-compat:
    jax.lax.axis_size where available, else the psum(1, axis) identity)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def _tree_bytes(tree):
    return sum(_telemetry.nbytes_of(x)
               for x in jax.tree_util.tree_leaves(tree))


def psum_tree(tree, mesh, axis="dp"):
    """Allreduce-sum a pytree of per-device arrays sharded over `axis`.

    Inputs are arrays sharded batch-first over the mesh axis; output is the
    sum, replicated. This is one jitted shard_map — XLA emits a single fused
    all-reduce for the whole tree (the multi-tensor aggregation the reference
    implements by hand in CommDevice::ReduceImpl).
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis),),
        out_specs=P(),
    )
    def _reduce(t):
        return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, axis), t)

    t0 = time.perf_counter()
    with _spans.span("psum", cat="collective"), _watchdog.guard("psum"):
        out = jax.jit(_reduce)(tree)
    _telemetry.record_collective("psum", _tree_bytes(tree),
                                 time.perf_counter() - t0)
    return out


def _flat_buckets(leaves, cap_bytes):
    """Partition leaf indices into dtype-homogeneous buckets of roughly
    `cap_bytes` each (order-preserving within a dtype). A leaf larger
    than the cap gets its own bucket — never split, never dropped."""
    buckets, open_by_dtype = [], {}
    for i, leaf in enumerate(leaves):
        nb = _telemetry.nbytes_of(leaf)
        cur = open_by_dtype.get(leaf.dtype)
        if cur is not None and cur[1] + nb <= cap_bytes:
            cur[0].append(i)
            open_by_dtype[leaf.dtype] = (cur[0], cur[1] + nb)
        else:
            fresh = [i]
            buckets.append(fresh)
            open_by_dtype[leaf.dtype] = (fresh, nb)
    return buckets


def _resolve_bucket_mb(bucket_mb):
    if bucket_mb is not None:
        return int(bucket_mb)
    from .. import env as _env

    return int(_env.get("MXTPU_FUSED_BUCKET_MB"))


def psum_tree_flat_traced(tree, axis, bucket_mb=None):
    """TRACED bucketed flat allreduce — the inside-the-program form of
    :func:`psum_tree_flat`, callable from code already running under
    ``shard_map`` (the whole-step compiled path threads its gradient
    allreduce through this, so reduce + optimizer update share one XLA
    program and one dispatch). Leaves are concatenated into
    dtype-homogeneous ~`bucket_mb` MB buffers, ONE ``lax.psum`` per
    buffer, split back to the original shapes in the same trace. No
    dispatch/telemetry bookkeeping here — the enclosing dispatch owns
    that; bucket sizes come from the (static) aval shapes."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    buckets = _flat_buckets(leaves, _resolve_bucket_mb(bucket_mb) << 20)
    outs = [None] * len(leaves)
    for bucket in buckets:
        flat = (leaves[bucket[0]].reshape(-1) if len(bucket) == 1
                else jnp.concatenate(
                    [leaves[i].reshape(-1) for i in bucket]))
        red = jax.lax.psum(flat, axis)
        off = 0
        for i in bucket:
            n = leaves[i].size
            outs[i] = red[off:off + n].reshape(leaves[i].shape)
            off += n
    return jax.tree_util.tree_unflatten(treedef, outs)


_flat_jit_cache = {}


def psum_tree_flat(tree, mesh, axis="dp", bucket_mb=None):
    """Bucketed flat allreduce of a pytree (the DDP-style multi-tensor
    path): leaves are flattened and concatenated into dtype-homogeneous
    buffers of ~`bucket_mb` MB, ONE ``lax.psum`` launches per buffer, and
    the buffer is split back to the original leaf shapes inside the SAME
    jitted shard_map — so a whole gradient tree costs O(buckets)
    collectives (typically 1-3) instead of O(leaves), with no extra
    dispatch for pack/unpack. Semantics match :func:`psum_tree`.
    `bucket_mb` defaults to ``MXTPU_FUSED_BUCKET_MB``.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    bucket_mb = _resolve_bucket_mb(bucket_mb)
    buckets = _flat_buckets(leaves, bucket_mb << 20)
    sig = (id(mesh), tuple(mesh.shape.items()), axis, bucket_mb,
           treedef, tuple((x.shape, str(x.dtype)) for x in leaves))
    fn = _flat_jit_cache.get(sig)
    if fn is None:
        @partial(shard_map, mesh=mesh, in_specs=(P(axis),), out_specs=P())
        def _reduce(ls):
            return psum_tree_flat_traced(ls, axis, bucket_mb)

        fn = jax.jit(_reduce)
        _flat_jit_cache[sig] = fn
    t0 = time.perf_counter()
    with _spans.span("psum_flat", cat="collective"), \
            _watchdog.guard("psum_flat"):
        outs = fn(leaves)
    _telemetry.record_collective("psum_flat", _tree_bytes(leaves),
                                 time.perf_counter() - t0)
    for bucket in buckets:
        _telemetry.record_fused_bucket("allreduce", len(bucket))
    return jax.tree_util.tree_unflatten(treedef, outs)


def allreduce_mean(tree, mesh, axis="dp"):
    n = mesh.shape[axis]
    summed = psum_tree(tree, mesh, axis)
    return jax.tree_util.tree_map(lambda x: x / n, summed)


def all_gather(x, mesh, axis="dp", tiled=True):
    """All-gather along a mesh axis (reference analog: broadcast fan-out)."""

    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(),
             check_vma=False)
    def _ag(v):
        return jax.lax.all_gather(v, axis, tiled=tiled)

    t0 = time.perf_counter()
    with _spans.span("all_gather", cat="collective"), \
            _watchdog.guard("all_gather"):
        out = jax.jit(_ag)(x)
    _telemetry.record_collective("all_gather", _tree_bytes(x),
                                 time.perf_counter() - t0)
    return out


def reduce_scatter(x, mesh, axis="dp"):
    """Reduce-scatter along a mesh axis (ZeRO-style sharded grads).

    Input: per-device full copies (replicated layout); output: each device
    keeps the reduced 1/n slice, laid out sharded over `axis`.
    """

    @partial(shard_map, mesh=mesh, in_specs=P(), out_specs=P(axis),
             check_vma=False)
    def _rs(v):
        return jax.lax.psum_scatter(v, axis, tiled=True)

    t0 = time.perf_counter()
    with _spans.span("reduce_scatter", cat="collective"), \
            _watchdog.guard("reduce_scatter"):
        out = jax.jit(_rs)(x)
    _telemetry.record_collective("reduce_scatter", _tree_bytes(x),
                                 time.perf_counter() - t0)
    return out


def ring_permute(x, mesh, axis="sp", shift=1):
    """Neighbor exchange along a ring — the building block of ring attention
    / context parallelism (a capability the reference lacks; SURVEY.md §5)."""
    n = mesh.shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    def _pp(v):
        return jax.lax.ppermute(v, axis, perm)

    t0 = time.perf_counter()
    with _spans.span("ppermute", cat="collective"), \
            _watchdog.guard("ppermute"):
        out = jax.jit(_pp)(x)
    _telemetry.record_collective("ppermute", _tree_bytes(x),
                                 time.perf_counter() - t0)
    return out
