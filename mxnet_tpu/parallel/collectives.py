"""Collective wrappers over XLA (psum/all_gather/reduce_scatter/ppermute).

These replace the reference's entire comm layer: CommCPU/CommDevice reduce
(src/kvstore/comm.h), tree allreduce (comm_tree.h), NCCL (kvstore_nccl.h) and
ps-lite push/pull — all become XLA collectives that ride ICI within a slice
and DCN across slices, scheduled asynchronously by the compiler.

Every wrapper records call count / input bytes / dispatch wall-time into
the telemetry registry (`collective_*` counters labeled by op — see
docs/telemetry.md). Dispatch time, not completion: the returned arrays are
async like everything else on the device stream.
"""
from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: experimental spelling, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma,
                              **kw)

from ..diagnostics import spans as _spans
from ..diagnostics import watchdog as _watchdog
from ..telemetry import instruments as _telemetry

__all__ = ["psum_tree", "allreduce_mean", "all_gather", "reduce_scatter",
           "ring_permute", "axis_size"]


def axis_size(axis_name):
    """Static size of a named mesh axis inside shard_map (version-compat:
    jax.lax.axis_size where available, else the psum(1, axis) identity)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def _tree_bytes(tree):
    return sum(_telemetry.nbytes_of(x)
               for x in jax.tree_util.tree_leaves(tree))


def psum_tree(tree, mesh, axis="dp"):
    """Allreduce-sum a pytree of per-device arrays sharded over `axis`.

    Inputs are arrays sharded batch-first over the mesh axis; output is the
    sum, replicated. This is one jitted shard_map — XLA emits a single fused
    all-reduce for the whole tree (the multi-tensor aggregation the reference
    implements by hand in CommDevice::ReduceImpl).
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis),),
        out_specs=P(),
    )
    def _reduce(t):
        return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, axis), t)

    t0 = time.perf_counter()
    with _spans.span("psum", cat="collective"), _watchdog.guard("psum"):
        out = jax.jit(_reduce)(tree)
    _telemetry.record_collective("psum", _tree_bytes(tree),
                                 time.perf_counter() - t0)
    return out


def allreduce_mean(tree, mesh, axis="dp"):
    n = mesh.shape[axis]
    summed = psum_tree(tree, mesh, axis)
    return jax.tree_util.tree_map(lambda x: x / n, summed)


def all_gather(x, mesh, axis="dp", tiled=True):
    """All-gather along a mesh axis (reference analog: broadcast fan-out)."""

    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(),
             check_vma=False)
    def _ag(v):
        return jax.lax.all_gather(v, axis, tiled=tiled)

    t0 = time.perf_counter()
    with _spans.span("all_gather", cat="collective"), \
            _watchdog.guard("all_gather"):
        out = jax.jit(_ag)(x)
    _telemetry.record_collective("all_gather", _tree_bytes(x),
                                 time.perf_counter() - t0)
    return out


def reduce_scatter(x, mesh, axis="dp"):
    """Reduce-scatter along a mesh axis (ZeRO-style sharded grads).

    Input: per-device full copies (replicated layout); output: each device
    keeps the reduced 1/n slice, laid out sharded over `axis`.
    """

    @partial(shard_map, mesh=mesh, in_specs=P(), out_specs=P(axis),
             check_vma=False)
    def _rs(v):
        return jax.lax.psum_scatter(v, axis, tiled=True)

    t0 = time.perf_counter()
    with _spans.span("reduce_scatter", cat="collective"), \
            _watchdog.guard("reduce_scatter"):
        out = jax.jit(_rs)(x)
    _telemetry.record_collective("reduce_scatter", _tree_bytes(x),
                                 time.perf_counter() - t0)
    return out


def ring_permute(x, mesh, axis="sp", shift=1):
    """Neighbor exchange along a ring — the building block of ring attention
    / context parallelism (a capability the reference lacks; SURVEY.md §5)."""
    n = mesh.shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    def _pp(v):
        return jax.lax.ppermute(v, axis, perm)

    t0 = time.perf_counter()
    with _spans.span("ppermute", cat="collective"), \
            _watchdog.guard("ppermute"):
        out = jax.jit(_pp)(x)
    _telemetry.record_collective("ppermute", _tree_bytes(x),
                                 time.perf_counter() - t0)
    return out
