"""Collective wrappers over XLA (psum/all_gather/reduce_scatter/ppermute).

These replace the reference's entire comm layer: CommCPU/CommDevice reduce
(src/kvstore/comm.h), tree allreduce (comm_tree.h), NCCL (kvstore_nccl.h) and
ps-lite push/pull — all become XLA collectives that ride ICI within a slice
and DCN across slices, scheduled asynchronously by the compiler.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = ["psum_tree", "allreduce_mean", "all_gather", "reduce_scatter",
           "ring_permute"]


def psum_tree(tree, mesh, axis="dp"):
    """Allreduce-sum a pytree of per-device arrays sharded over `axis`.

    Inputs are arrays sharded batch-first over the mesh axis; output is the
    sum, replicated. This is one jitted shard_map — XLA emits a single fused
    all-reduce for the whole tree (the multi-tensor aggregation the reference
    implements by hand in CommDevice::ReduceImpl).
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis),),
        out_specs=P(),
    )
    def _reduce(t):
        return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, axis), t)

    return jax.jit(_reduce)(tree)


def allreduce_mean(tree, mesh, axis="dp"):
    n = mesh.shape[axis]
    summed = psum_tree(tree, mesh, axis)
    return jax.tree_util.tree_map(lambda x: x / n, summed)


def all_gather(x, mesh, axis="dp", tiled=True):
    """All-gather along a mesh axis (reference analog: broadcast fan-out)."""

    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(),
             check_vma=False)
    def _ag(v):
        return jax.lax.all_gather(v, axis, tiled=tiled)

    return jax.jit(_ag)(x)


def reduce_scatter(x, mesh, axis="dp"):
    """Reduce-scatter along a mesh axis (ZeRO-style sharded grads).

    Input: per-device full copies (replicated layout); output: each device
    keeps the reduced 1/n slice, laid out sharded over `axis`.
    """

    @partial(shard_map, mesh=mesh, in_specs=P(), out_specs=P(axis),
             check_vma=False)
    def _rs(v):
        return jax.lax.psum_scatter(v, axis, tiled=True)

    return jax.jit(_rs)(x)


def ring_permute(x, mesh, axis="sp", shift=1):
    """Neighbor exchange along a ring — the building block of ring attention
    / context parallelism (a capability the reference lacks; SURVEY.md §5)."""
    n = mesh.shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    def _pp(v):
        return jax.lax.ppermute(v, axis, perm)

    return jax.jit(_pp)(x)
