"""Monitor — per-op output inspection during training
(reference: python/mxnet/monitor.py: installs output callbacks on the
executor and prints stat summaries per batch).

TPU re-design: rides Gluon's register_op_hook (the CachedOp::RegisterOpHook
analog): Monitor.install(net) attaches a forward hook to every child block
recording `stat_func` of each output; tic()/toc() bracket a batch and
return the collected (name, stat) rows like the reference's toc_print.
"""
from __future__ import annotations

import logging
import re

import jax.numpy as jnp

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval=1, stat_func=None, pattern=".*", sort=False):
        self.interval = int(interval)
        self.stat_func = stat_func or (
            lambda x: jnp.abs(x).mean())  # reference default: mean(|x|)
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.queue = []
        self.step = 0
        self.activated = False
        self._handles = []

    # -- installation ------------------------------------------------------
    def install(self, net, monitor_all=False):  # noqa: ARG002
        """Attach to every block in `net` (reference: install_executor)."""

        def hook(block, inputs, outputs):  # noqa: ARG001
            if not self.activated:
                return
            name = type(block).__name__
            if not self.re_pattern.match(name):
                return
            outs = outputs if isinstance(outputs, (list, tuple)) else \
                [outputs]
            for i, o in enumerate(outs):
                data = getattr(o, "_data", o)
                try:
                    self.queue.append(
                        (self.step, f"{name}_output{i}",
                         self.stat_func(jnp.asarray(data))))
                except TypeError:
                    pass

        if self._handles:
            self.uninstall()  # re-install must not double-count

        def walk(block):
            block.register_forward_hook(hook)
            self._handles.append((block, hook))
            for child in block._children.values():
                walk(child)

        walk(net)
        return self

    def uninstall(self):
        """Remove every hook this monitor installed."""
        for block, hook in self._handles:
            hooks = getattr(block, "_fwd_hooks", [])
            if hook in hooks:
                hooks.remove(hook)
        self._handles = []

    # -- batch bracketing --------------------------------------------------
    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []

    def toc(self):
        if not self.activated:
            self.step += 1
            return []
        self.activated = False
        res = [(s, name, float(val)) for s, name, val in self.queue]
        if self.sort:
            res.sort(key=lambda r: r[1])
        self.queue = []
        self.step += 1
        return res

    def toc_print(self):
        for step, name, value in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, value)
