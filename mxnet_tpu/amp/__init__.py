"""AMP — automatic mixed precision (reference: python/mxnet/amp/, 2321 LoC).

TPU re-design: bf16 is the native mixed-precision dtype; unlike fp16-on-GPU,
bf16's fp32-range exponent makes loss scaling unnecessary (the reference's
dynamic LossScaler exists for fp16 and is kept as an API shim). The
reference's cast-list machinery (amp/lists/symbol_fp16.py) maps to a simple
policy: matmul/conv compute in bf16, reductions/norms accumulate in fp32 —
which XLA does automatically once params/inputs are bf16 and normalization
ops upcast internally (see ops/nn.py batch_norm/rms_norm).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..base import normalize_dtype
from ..ndarray.ndarray import NDArray

from . import lists  # noqa: E402  (reference: amp/lists/ cast tables)

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "convert_model", "convert_symbol",
           "LossScaler", "lists", "warn_if_model_exists",
           "list_lp16_ops", "list_fp32_ops", "list_lp16_fp32_ops",
           "list_conditional_fp32_ops", "list_widest_type_cast",
           "list_loss_output_functions", "list_lp16_use_fp32_params"]

_initialized = False
_target_dtype = "bfloat16"

# back-compat aliases of the canonical tables in lists/symbol_bf16.py
_FP32_OPS = lists.symbol_bf16.FP32_FUNCS
_LP16_OPS = lists.symbol_bf16.BF16_FUNCS


def list_lp16_ops(target_dtype="bfloat16"):  # noqa: ARG001
    """Reference: amp/amp.py:769 — both fp16 and bf16 answer the TPU
    (bf16) table; see lists/symbol_fp16.py."""
    return list(_LP16_OPS)


def list_fp32_ops(target_dtype="bfloat16"):  # noqa: ARG001
    return list(_FP32_OPS)


def list_lp16_fp32_ops(target_dtype="bfloat16"):  # noqa: ARG001
    """Ops that run in either precision (reference: amp/amp.py:787)."""
    return list(lists.symbol_bf16.BF16_FP32_FUNCS)


def list_conditional_fp32_ops(target_dtype="bfloat16"):  # noqa: ARG001
    return list(lists.symbol_bf16.CONDITIONAL_FP32_FUNCS)


def list_widest_type_cast(target_dtype="bfloat16"):  # noqa: ARG001
    return list(lists.symbol_bf16.WIDEST_TYPE_CASTS)


def list_loss_output_functions(target_dtype="bfloat16"):  # noqa: ARG001
    return list(lists.symbol_bf16.LOSS_OUTPUT_FUNCTIONS)


def list_lp16_use_fp32_params(target_dtype="bfloat16"):  # noqa: ARG001
    """Reference: amp/amp.py:823 — None for fp16; the param-restrict map
    for bf16."""
    if target_dtype in ("float16", "fp16", _np.float16):
        return None
    return dict(lists.symbol_bf16.BF16_USE_FP32_PARAMS)


def warn_if_model_exists():
    """Warn about Blocks created before amp.init (reference:
    amp/amp.py:301 — walks the caller stack for Block locals)."""
    import inspect
    import logging

    from ..gluon.block import Block

    for f in inspect.stack():
        for k, v in f.frame.f_locals.items():
            if isinstance(v, Block):
                logging.warning("Block %s created in [%s:%d] before "
                                "AMP init.", k, f.filename, f.lineno)
                return


def convert_symbol(sym, target_dtype="bfloat16", target_dtype_ops=None,
                   fp32_ops=None, conditional_fp32_ops=None,
                   excluded_sym_names=None, data_names=None,
                   cast_optional_params=False):  # noqa: ARG001
    """Convert a Symbol to mixed precision (reference: amp/amp.py:430
    low_precision_pass over the nnvm graph). TPU-native: wraps the DAG in
    one `_amp_graph` node whose lowering traces the original graph to a
    jaxpr and rewrites it under the cast lists (amp.graph_pass.
    amp_rewrite) — outputs keep their original dtypes, matmuls/convs run
    bf16 on the MXU."""
    from ..symbol.symbol import Symbol

    if not isinstance(sym, Symbol):
        raise TypeError(f"convert_symbol expects a Symbol, got {type(sym)}")
    dt = "bfloat16" if target_dtype in ("float16", "fp16", "bfloat16",
                                        "bf16", _np.float16) \
        else str(target_dtype)
    leaves = {}
    for s in sym._topo():
        if s._op is None and s._name not in leaves:
            leaves[s._name] = s
    import json as _json
    return Symbol.create(
        "_amp_graph", *leaves.values(), name=f"amp_{sym.name}",
        nout=len(sym.list_outputs()),
        subgraph=sym.tojson(),
        in_names=_json.dumps(list(leaves)),
        target_dtype=dt)


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):  # noqa: ARG001
    """Enable AMP (reference: amp.init). On TPU this sets the default policy
    used by convert_hybrid_block / Trainer AMP hooks."""
    global _initialized, _target_dtype
    _target_dtype = "bfloat16" if target_dtype in ("float16", "fp16",
                                                   "bfloat16", "bf16") \
        else target_dtype
    _initialized = True


def init_trainer(trainer):
    """Attach a loss scaler to the trainer (fp16 parity; no-op for bf16)."""
    trainer._amp_loss_scaler = LossScaler()
    return trainer


def scale_loss(loss, trainer):
    """Context manager scaling the loss (reference: amp.scale_loss).

    bf16 needs no scaling; returned object supports `with` and yields the
    (unscaled) loss for drop-in compatibility.
    """
    import contextlib

    scaler = getattr(trainer, "_amp_loss_scaler", None)

    @contextlib.contextmanager
    def ctx():
        if scaler is None or _target_dtype == "bfloat16":
            yield loss
        else:
            scaled = loss * scaler.loss_scale
            yield scaled

    return ctx()


def unscale(trainer):
    """Unscale gradients after backward (fp16 path; bf16 no-op)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null":
            for g in p.list_grad():
                g._data = g._data * inv
                g._version += 1


def _cast_param(p, dtype, keep_fp32=False):
    name = p.name.lower()
    # norms' scale/shift and running stats stay fp32 (cast-list analog)
    if keep_fp32 or any(k in name for k in ("gamma", "beta", "running",
                                            "moving")):
        return
    p.cast(dtype)


def convert_hybrid_block(net, target_dtype="bfloat16", target_dtype_ops=None,
                         fp32_ops=None, conditional_fp32_ops=None,
                         excluded_sym_names=None, device=None,
                         cast_params_offline=True, graph_pass=False,
                         example_inputs=None):  # noqa: ARG001
    """Convert a HybridBlock to mixed precision (reference: amp.py:676
    convert_hybrid_block): params cast to bf16 except norm/scale params;
    the compiled program then runs matmuls/convs on the MXU in bf16.

    ``graph_pass=True`` is the reference's *graph-level* cast conversion
    (low_precision_pass.cc — every op forced through the cast lists
    regardless of how it was written): instead of casting params, the
    block's pass pipeline (docs/passes.md) gains passes.AmpPass, so
    every compiled variant — block jit, export, symbol lowering, the
    whole-step train program's forward — is rewritten under the cast
    lists.  Pass ``example_inputs`` (a tuple) to build the first
    variant eagerly and fill ``net._amp_stats`` before returning.
    """
    dtype = normalize_dtype("bfloat16" if target_dtype in (
        "float16", "fp16", "bfloat16", "bf16") else target_dtype)
    if graph_pass:
        from .graph_pass import convert_block_graph

        if example_inputs is not None:
            convert_block_graph(net, tuple(example_inputs), dtype)
        else:
            from .. import passes as _passes

            net.hybridize(True)
            net.pass_pipeline().register(_passes.AmpPass(dtype))
            net._jit_variants.clear()
        return net
    for p in net.collect_params().values():
        if p._data_map is not None or p.shape is not None:
            _cast_param(p, dtype)
    net._clear_cached()
    # wrap forward so inputs are cast on entry
    orig_forward = net.forward

    def forward(*args):
        cast_args = [
            a.astype(dtype) if isinstance(a, NDArray)
            and _np.issubdtype(a.dtype, _np.floating) else a
            for a in args
        ]
        return orig_forward(*cast_args)

    net.forward = forward
    return net


convert_model = convert_hybrid_block


class LossScaler:
    """Dynamic loss scaler (reference: amp/loss_scaler.py). Needed for fp16
    only; bf16 training keeps scale 1."""

    def __init__(self, init_scale=2 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = 1.0 if _target_dtype == "bfloat16" else init_scale
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0
        self._check_cache = {}  # (shape, dtype) signature -> jitted check

    def has_overflow(self, params):
        """True when any gradient holds a non-finite value — ONE fused
        device reduction (the multi_all_finite kernel) and ONE host sync
        per step, instead of a per-array isfinite + sync loop."""
        grads = [p.grad()._data for p in params if p.grad_req != "null"]
        if not grads:
            return False
        import jax

        from ..ops.optimizer_ops import multi_all_finite

        sig = tuple((g.shape, str(g.dtype)) for g in grads)
        fn = self._check_cache.get(sig)
        if fn is None:
            fn = self._check_cache[sig] = jax.jit(
                lambda *gs: multi_all_finite(*gs))
        overflow = not bool(fn(*grads)[0])  # the step's one host sync
        if overflow:
            try:
                from ..observability import flight as _flight

                _flight.record("amp_overflow", arrays=len(grads),
                               loss_scale=float(self.loss_scale))
            except Exception:
                pass
        return overflow

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale *= self._factor
                self._unskipped = 0


from . import graph_pass  # noqa: E402
from .graph_pass import convert_block_graph  # noqa: E402


def _amp_graph_lower(ins, attrs):
    """Symbol-op lowering for convert_symbol's `_amp_graph` node: rebuild
    the wrapped DAG, trace it to a jaxpr at the incoming shapes, and run
    it under the AMP cast lists."""
    import json as _json

    import jax

    from ..symbol.symbol import fromjson
    from .graph_pass import amp_rewrite

    subfn = fromjson(attrs["subgraph"])._lower()
    names = _json.loads(attrs["in_names"])
    dt = jnp.bfloat16 if attrs["target_dtype"] in ("bfloat16", "bf16") \
        else jnp.dtype(attrs["target_dtype"])
    closed = jax.make_jaxpr(
        lambda *xs: tuple(subfn(dict(zip(names, xs)))))(*ins)
    outs = amp_rewrite(closed, dt)(*ins)
    return tuple(outs) if len(outs) > 1 else outs[0]


def _register_amp_sym_op():
    from ..symbol.symbol import register_sym_op

    register_sym_op("_amp_graph", _amp_graph_lower)


_register_amp_sym_op()
