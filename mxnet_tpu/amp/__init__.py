"""AMP — automatic mixed precision (reference: python/mxnet/amp/, 2321 LoC).

TPU re-design: bf16 is the native mixed-precision dtype; unlike fp16-on-GPU,
bf16's fp32-range exponent makes loss scaling unnecessary (the reference's
dynamic LossScaler exists for fp16 and is kept as an API shim). The
reference's cast-list machinery (amp/lists/symbol_fp16.py) maps to a simple
policy: matmul/conv compute in bf16, reductions/norms accumulate in fp32 —
which XLA does automatically once params/inputs are bf16 and normalization
ops upcast internally (see ops/nn.py batch_norm/rms_norm).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..base import normalize_dtype
from ..ndarray.ndarray import NDArray

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "convert_model", "LossScaler",
           "list_lp16_ops", "list_fp32_ops"]

_initialized = False
_target_dtype = "bfloat16"

# op classes that stay fp32 under AMP (the reference's FP32_FUNCS analog):
# softmax/log/exp/norms accumulate in fp32 inside their implementations.
_FP32_OPS = ["softmax", "log_softmax", "batch_norm", "layer_norm",
             "group_norm", "instance_norm", "rms_norm", "norm", "mean",
             "sum", "exp", "log"]
_LP16_OPS = ["convolution", "deconvolution", "fully_connected", "matmul",
             "dot", "einsum", "rnn"]


def list_lp16_ops(target_dtype="bfloat16"):  # noqa: ARG001
    return list(_LP16_OPS)


def list_fp32_ops(target_dtype="bfloat16"):  # noqa: ARG001
    return list(_FP32_OPS)


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):  # noqa: ARG001
    """Enable AMP (reference: amp.init). On TPU this sets the default policy
    used by convert_hybrid_block / Trainer AMP hooks."""
    global _initialized, _target_dtype
    _target_dtype = "bfloat16" if target_dtype in ("float16", "fp16",
                                                   "bfloat16", "bf16") \
        else target_dtype
    _initialized = True


def init_trainer(trainer):
    """Attach a loss scaler to the trainer (fp16 parity; no-op for bf16)."""
    trainer._amp_loss_scaler = LossScaler()
    return trainer


def scale_loss(loss, trainer):
    """Context manager scaling the loss (reference: amp.scale_loss).

    bf16 needs no scaling; returned object supports `with` and yields the
    (unscaled) loss for drop-in compatibility.
    """
    import contextlib

    scaler = getattr(trainer, "_amp_loss_scaler", None)

    @contextlib.contextmanager
    def ctx():
        if scaler is None or _target_dtype == "bfloat16":
            yield loss
        else:
            scaled = loss * scaler.loss_scale
            yield scaled

    return ctx()


def unscale(trainer):
    """Unscale gradients after backward (fp16 path; bf16 no-op)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null":
            for g in p.list_grad():
                g._data = g._data * inv
                g._version += 1


def _cast_param(p, dtype, keep_fp32=False):
    name = p.name.lower()
    # norms' scale/shift and running stats stay fp32 (cast-list analog)
    if keep_fp32 or any(k in name for k in ("gamma", "beta", "running",
                                            "moving")):
        return
    p.cast(dtype)


def convert_hybrid_block(net, target_dtype="bfloat16", target_dtype_ops=None,
                         fp32_ops=None, conditional_fp32_ops=None,
                         excluded_sym_names=None, device=None,
                         cast_params_offline=True):  # noqa: ARG001
    """Convert a HybridBlock to mixed precision (reference: amp.py:676
    convert_hybrid_block): params cast to bf16 except norm/scale params;
    the compiled program then runs matmuls/convs on the MXU in bf16.

    For the reference's *graph-level* cast conversion
    (low_precision_pass.cc — every op forced through the cast lists
    regardless of how it was written), see
    amp.graph_pass.convert_block_graph, which rewrites the traced jaxpr.
    """
    dtype = normalize_dtype("bfloat16" if target_dtype in (
        "float16", "fp16", "bfloat16", "bf16") else target_dtype)
    for p in net.collect_params().values():
        if p._data_map is not None or p.shape is not None:
            _cast_param(p, dtype)
    net._clear_cached()
    # wrap forward so inputs are cast on entry
    orig_forward = net.forward

    def forward(*args):
        cast_args = [
            a.astype(dtype) if isinstance(a, NDArray)
            and _np.issubdtype(a.dtype, _np.floating) else a
            for a in args
        ]
        return orig_forward(*cast_args)

    net.forward = forward
    return net


convert_model = convert_hybrid_block


class LossScaler:
    """Dynamic loss scaler (reference: amp/loss_scaler.py). Needed for fp16
    only; bf16 training keeps scale 1."""

    def __init__(self, init_scale=2 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = 1.0 if _target_dtype == "bfloat16" else init_scale
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        for p in params:
            if p.grad_req == "null":
                continue
            g = p.grad()
            if not bool(jnp.isfinite(g._data).all()):
                return True
        return False

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale *= self._factor
                self._unskipped = 0


from . import graph_pass  # noqa: E402
from .graph_pass import convert_block_graph  # noqa: E402
