"""AMP as a graph pass (reference: src/nnvm/low_precision_pass.cc + the
amp/lists cast-list machinery — ReducePrecision graph conversion that
selectively wraps ops in casts, rather than just casting parameters).

TPU re-design: the traced jaxpr is rewritten by an interpreter that
enforces the cast lists at every equation:
  * LP16 ops (the FLOP carriers: dot_general, conv) run in bfloat16 —
    float32 operands are cast down at the op boundary;
  * FP32 ops (numerically sensitive: exp/log/softmax chain, norms'
    rsqrt, reductions) run in float32 — low-precision operands are cast
    up, so a user-written eager op accumulates in fp32 *by construction*
    (the round-1 gap: _FP32_OPS was a comment-level contract);
  * everything else runs in the widest float dtype among its operands;
  * graph outputs are cast back to their original dtypes.

`convert_hybrid_block(net, graph_pass=True)` installs the rewritten
program as the block's compiled variant.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.extend import core as jcore

__all__ = ["amp_rewrite", "AmpStats", "LP16_PRIMS", "FP32_PRIMS",
           "build_amp_variant", "convert_block_graph"]

# the FLOP carriers — MXU ops that bf16 accelerates
LP16_PRIMS = frozenset({"dot_general", "conv_general_dilated"})

# numerically-sensitive ops pinned to fp32 (reference: amp/lists FP32 ops)
FP32_PRIMS = frozenset({
    "exp", "log", "log1p", "expm1", "rsqrt", "sqrt", "erf", "erf_inv",
    "lgamma", "digamma", "reduce_sum", "reduce_max", "reduce_min",
    "reduce_prod", "cumsum", "cumlogsumexp", "logistic", "tanh", "pow",
    "integer_pow", "div", "atan2",
})

_FLOAT_DTYPES = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


class AmpStats:
    """Counts of cast decisions — observability for tests/debugging."""

    def __init__(self):
        self.lp16_ops = 0
        self.fp32_pinned_ops = 0

    def __repr__(self):
        return (f"AmpStats(lp16_ops={self.lp16_ops}, "
                f"fp32_pinned_ops={self.fp32_pinned_ops})")


def _is_float(x):
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _cast_floats(vals, dtype):
    return [v.astype(dtype) if _is_float(v) and v.dtype != dtype else v
            for v in vals]


def _widest_float(vals):
    widest = None
    for v in vals:
        if _is_float(v):
            if widest is None or jnp.finfo(v.dtype).bits > \
                    jnp.finfo(widest).bits:
                widest = v.dtype
    return widest


def amp_rewrite(closed_jaxpr, target_dtype=jnp.bfloat16, stats=None):
    """Return callable(*flat_args) executing the jaxpr under the AMP cast
    lists. Outputs are cast back to the original output dtypes."""
    from ..subgraph import _eval_eqn

    jaxpr = closed_jaxpr.jaxpr
    consts = closed_jaxpr.consts
    out_dtypes = [getattr(v.aval, "dtype", None) for v in jaxpr.outvars]
    stats = stats if stats is not None else AmpStats()

    # decide once at rewrite time (trace-time work, not per step)
    plan = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in LP16_PRIMS:
            plan.append("lp16")
            stats.lp16_ops += 1
        elif name in FP32_PRIMS:
            plan.append("fp32")
            stats.fp32_pinned_ops += 1
        elif name in ("pjit", "closed_call", "custom_jvp_call",
                      "custom_vjp_call", "custom_vjp_call_jaxpr",
                      "remat2", "checkpoint", "convert_element_type"):
            plan.append("exact")  # opaque bodies / explicit user casts
        else:
            plan.append("widest")

    def run(*args):
        env = {}

        def read(v):
            if isinstance(v, jcore.Literal):
                return jnp.asarray(v.val)
            return env[v]

        for v, c in zip(jaxpr.constvars, consts):
            env[v] = c
        for v, a in zip(jaxpr.invars, args):
            env[v] = a

        for eqn, decision in zip(jaxpr.eqns, plan):
            invals = [read(v) for v in eqn.invars]
            if decision == "lp16":
                invals = _cast_floats(invals, target_dtype)
            elif decision == "fp32":
                invals = _cast_floats(invals, jnp.float32)
            elif decision == "exact":
                # opaque call bodies expect their recorded operand dtypes
                invals = [
                    val.astype(v.aval.dtype)
                    if _is_float(val) and hasattr(v.aval, "dtype")
                    and jnp.issubdtype(v.aval.dtype, jnp.floating)
                    and val.dtype != v.aval.dtype else val
                    for val, v in zip(invals, eqn.invars)]
            else:
                w = _widest_float(invals)
                if w is not None:
                    invals = _cast_floats(invals, w)
            out = _eval_eqn(eqn, invals)
            if isinstance(out, (tuple, list)):
                for v, val in zip(eqn.outvars, out):
                    env[v] = val
            else:
                env[eqn.outvars[0]] = out

        outs = []
        for v, dt in zip(jaxpr.outvars, out_dtypes):
            val = read(v)
            if dt is not None and _is_float(val) and val.dtype != dt:
                val = val.astype(dt)
            outs.append(val)
        return outs

    run._amp_stats = stats
    return run


def build_amp_variant(cached_fn, target_dtype, pd, key, datas):
    """Trace + AMP-rewrite one compiled variant. Returns (jitted, stats).
    Legacy one-off builder, now a thin veneer over the pass pipeline
    (passes.AmpPass via apply_pipeline) so jit construction for
    captured bodies lives in ONE place; the eval_shape builds the
    pipeline entry eagerly (abstract — no compute) so stats are filled
    on return, as before."""
    from .. import passes as _passes

    stats = AmpStats()
    ctx = _passes.PassContext(label="amp_variant", kind="block")
    jitted = _passes.apply_pipeline(
        cached_fn, [_passes.AmpPass(target_dtype, stats=stats)], ctx)
    jax.eval_shape(jitted, pd, key, *datas)
    return jitted, stats


def convert_block_graph(block, example_inputs, target_dtype=jnp.bfloat16):
    """Enable the AMP graph pass on a HybridBlock: registers
    passes.AmpPass on the block's pass pipeline, so the traced jaxpr is
    rewritten under the cast lists for EVERY compiled variant — block
    jit, export, symbol lowering — now and on every rebuild.  Returns
    the AmpStats of the eagerly-built variant.  (The graph-pass mode of
    amp.convert_hybrid_block.)"""
    from .. import passes as _passes

    block.hybridize(True)
    block.pass_pipeline().register(_passes.AmpPass(target_dtype))
    block._jit_variants.clear()
    block(*example_inputs)  # force one build so stats are available
    return block._amp_stats
