"""fp16 AMP cast lists (reference: amp/lists/symbol_fp16.py).

TPU note: the MXU computes in bf16; float16 is supported for storage/API
compatibility, and its cast policy is the bf16 policy (same op classes,
same accumulation-sensitivity analysis) — kept as a distinct module so
reference spellings (`amp.lists.symbol_fp16.FP16_FUNCS`) resolve.
"""
from .symbol_bf16 import (
    BF16_FP32_FUNCS as FP16_FP32_FUNCS,  # noqa: F401
    BF16_FUNCS as FP16_FUNCS,  # noqa: F401
    CONDITIONAL_FP32_FUNCS,  # noqa: F401
    FP32_FUNCS,  # noqa: F401
    LOSS_OUTPUT_FUNCTIONS,  # noqa: F401
    WIDEST_TYPE_CASTS,  # noqa: F401
)
