"""AMP op cast lists (reference: python/mxnet/amp/lists/__init__.py)."""
from . import symbol_bf16, symbol_fp16  # noqa: F401
