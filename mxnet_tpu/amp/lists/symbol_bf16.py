"""bf16 AMP cast lists (reference: amp/lists/symbol_bf16.py — BF16_FUNCS,
BF16_FP32_FUNCS, FP32_FUNCS, CONDITIONAL_FP32_FUNCS, WIDEST_TYPE_CASTS,
LOSS_OUTPUT_FUNCTIONS, BF16_USE_FP32_PARAMS).

TPU note: bf16 is the MXU-native low precision, so this is the list that
actually drives `amp.convert_*` here. Names are *op classes* of this
framework's registry; the graph pass works at jaxpr-primitive level
(amp.graph_pass.LP16_PRIMS / FP32_PRIMS) — these lists are the op-level
view of the same policy.
"""

# MXU-bound ops forced to bf16: the FLOPs live here
BF16_FUNCS = [
    "Convolution", "Deconvolution", "FullyConnected", "convolution",
    "deconvolution", "fully_connected", "matmul", "dot", "batch_dot",
    "einsum", "RNN", "rnn",
]

# numerically safe in either precision — left at the input dtype
BF16_FP32_FUNCS = [
    "abs", "add_n", "broadcast_add", "broadcast_sub", "broadcast_mul",
    "broadcast_div", "clip", "concat", "elemwise_add", "elemwise_sub",
    "elemwise_mul", "elemwise_div", "flatten", "maximum", "minimum",
    "negative", "relu", "reshape", "slice", "split", "squeeze", "stack",
    "tile", "transpose", "where", "Activation", "Pooling", "pooling",
    "pad", "take", "embedding", "Embedding",
]

# accumulation-sensitive: pinned fp32 (stat/reduction paths accumulate in
# fp32 inside the implementations — ops/nn.py norm stats)
FP32_FUNCS = [
    "softmax", "log_softmax", "SoftmaxActivation", "BatchNorm",
    "batch_norm", "LayerNorm", "layer_norm", "GroupNorm", "group_norm",
    "InstanceNorm", "instance_norm", "rms_norm", "L2Normalization",
    "norm", "mean", "sum", "prod", "exp", "log", "log1p", "expm1",
    "erf", "erfinv", "gamma", "gammaln", "smooth_l1", "topk", "sort",
    "argsort",
]

# fp32 only under certain attrs (reference: e.g. Activation softrelu)
CONDITIONAL_FP32_FUNCS = [
    ("Activation", "act_type", ["softrelu"]),
]

# multi-input elementwise ops cast to the widest input dtype
WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_power",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "concat", "stack", "where", "add_n",
]

# loss outputs stay at full precision for stable gradients
LOSS_OUTPUT_FUNCTIONS = [
    "SoftmaxOutput", "softmax_cross_entropy", "LinearRegressionOutput",
    "LogisticRegressionOutput", "MAERegressionOutput",
    "MakeLoss", "make_loss",
]

# ops whose *params* stay fp32 while activations run bf16 (norm scale/
# shift and running stats — amp._cast_param applies this rule)
BF16_USE_FP32_PARAMS = {
    "BatchNorm": ["gamma", "beta", "moving_mean", "moving_var"],
    "LayerNorm": ["gamma", "beta"],
    "GroupNorm": ["gamma", "beta"],
    "InstanceNorm": ["gamma", "beta"],
}
