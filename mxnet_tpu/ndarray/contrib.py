"""`mx.nd.contrib` namespace (reference: mxnet/ndarray/contrib.py).
The contrib op corpus under its legacy spelling."""
from ..contrib.ops import *  # noqa: F401,F403
from ..contrib.ops import __all__  # noqa: F401
