"""`mx.nd.contrib` namespace (reference: mxnet/ndarray/contrib.py).

Two populations, same as the reference file: the contrib op corpus under
its legacy spelling (generated there, registry-driven here), and the
hand-written helpers the reference defines directly in
ndarray/contrib.py — control flow (foreach:139, while_loop:233, cond:401),
the float-test trio (isinf:467, isfinite:493, isnan:522), and
rand_zipfian:39.
"""
import math

from ..contrib.ops import *  # noqa: F401,F403
from ..contrib.ops import __all__ as _ops_all

# control flow: eager versions lower to lax.scan/while_loop
# (reference routes these through a CachedOp over a cut subgraph;
# numpy_extension.control_flow is the shared TPU-native implementation)
from ..numpy_extension.control_flow import (  # noqa: F401
    foreach,
    while_loop,
)


def cond(pred, then_func, else_func):
    """Eager if-then-else (reference: ndarray/contrib.py:401): `pred` is a
    scalar NDArray; then/else take NO arguments and close over their
    operands; only the taken branch executes (and is taped)."""
    import numpy as _onp

    branch = bool(_onp.asarray(
        pred.asnumpy() if hasattr(pred, "asnumpy") else pred).reshape(()))
    return then_func() if branch else else_func()

__all__ = list(_ops_all) + [
    "foreach", "while_loop", "cond",
    "isinf", "isfinite", "isnan", "rand_zipfian",
]


def isinf(data):
    """1.0 where the element is +/-inf, else 0.0 (reference:
    ndarray/contrib.py:467 — returns float, not bool)."""
    return (abs(data) == float("inf")).astype(data.dtype)


def isfinite(data):
    """1.0 where the element is finite (reference: ndarray/contrib.py:493)."""
    not_nan = data == data
    not_inf = abs(data) != float("inf")
    return (not_inf * not_nan).astype(data.dtype)


def isnan(data):
    """1.0 where the element is NaN (reference: ndarray/contrib.py:522)."""
    return (data != data).astype(data.dtype)


def rand_zipfian(true_classes, num_sampled, range_max, ctx=None):  # noqa: ARG001
    """Log-uniform (Zipfian) candidate sampler (reference:
    ndarray/contrib.py:39): P(class) = (log(class+2) - log(class+1)) /
    log(range_max+1). Returns (samples int, expected_count_true,
    expected_count_sampled)."""
    from ..numpy import random as _random

    log_range = math.log(range_max + 1)
    rand = _random.uniform(0, log_range, size=(num_sampled,))
    sampled_classes = (rand.exp() - 1).astype("int64") % range_max

    true_cls = true_classes.astype("float64")
    expected_count_true = (
        ((true_cls + 2.0) / (true_cls + 1.0)).log() / log_range * num_sampled)
    sampled_f = sampled_classes.astype("float64")
    expected_prob_sampled = (
        ((sampled_f + 2.0) / (sampled_f + 1.0)).log() / log_range)
    return sampled_classes, expected_count_true, \
        expected_prob_sampled * num_sampled


def SparseEmbedding(data, weight, input_dim=None, output_dim=None,  # noqa: N802
                    dtype=None, deterministic=False, **kwargs):  # noqa: ARG001
    """Deprecated reference spelling (indexing_op.cc
    _contrib_SparseEmbedding): Embedding whose weight gradient is row
    sparse; `nn.Embedding(..., sparse_grad=True)` is the modern path —
    this alias delegates to the same kernel."""
    from ..ops.nn import embedding

    from .ndarray import apply_op

    return apply_op(
        lambda d, w: embedding(d, w, input_dim=input_dim,
                               output_dim=output_dim, dtype=dtype,
                               sparse_grad=True),
        data, weight, name="SparseEmbedding")
