"""mx.nd — the legacy imperative NDArray namespace.

Reference: python/mxnet/ndarray/ (24k LoC of *generated* wrappers over the
NNVM registry — python/mxnet/ndarray/register.py). Same design here: the
namespace is populated at import time from the pure-op registry
(mxnet_tpu/ops/), so every registered op — elemwise/broadcast families,
reductions, ordering, indexing, matrix ops, the `linalg_*` la_op family, the
legacy vision ops (BilinearSampler, SpatialTransformer, ROIPooling,
Correlation, DeformableConvolution, GridGenerator), CamelCase v1 NN ops and
the loss-output ops — resolves as `mx.nd.<name>` with reference call
signatures, eager async execution, and autograd taping.
"""
from ..numpy import (  # noqa: F401
    arange,
    array,
    concatenate,
    full,
    linspace,
    ones,
    ones_like,
    zeros,
    zeros_like,
)
from . import linalg  # noqa: F401
from . import random  # noqa: F401
from . import sparse  # noqa: F401
from . import _internal  # noqa: F401
from . import image  # noqa: F401
from . import op  # noqa: F401
from .ndarray import NDArray, apply_op, from_jax, waitall  # noqa: F401
from . import contrib  # noqa: F401  (after .ndarray: contrib ops use apply_op)
from .register import make_eager, populate

# numpy-flavored submodules under the legacy package (reference:
# ndarray/__init__.py:20 imports .numpy / .numpy_extension; here the
# numpy frontend is one shared package, not re-generated per frontend)
from .. import numpy  # noqa: F401,E402
from .. import numpy as np  # noqa: F401,E402
from .. import numpy_extension  # noqa: F401,E402
from .. import numpy_extension as npx  # noqa: F401,E402
from .utils import load, save, savez  # noqa: F401


def empty(shape, ctx=None, dtype=None):  # noqa: ARG001
    """Allocate without defined contents (reference: nd.empty —
    grad/output buffers; zero-filled here, jax arrays are immutable)."""
    return zeros(shape, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, infer_range=None,  # noqa: A001
           ctx=None, dtype="float32", **kwargs):  # noqa: ARG001
    """Legacy arange (reference: ndarray/ndarray.py:3510): default dtype
    is float32 (mx_real_t) even for int args; `repeat` tiles each element
    consecutively — arange(2,6,step=2,repeat=3) -> [2,2,2,4,4,4]."""
    from ..numpy import arange as _np_arange

    out = _np_arange(start, stop, step, dtype=dtype)
    if repeat != 1:
        out = out.repeat(repeat)
    return out


def split_v2(ary, indices_or_sections, axis=0, squeeze_axis=False):
    """Reference nd.split_v2 (matrix_op.cc SplitV2): int = n equal
    sections, sequence = cut points; squeeze_axis drops the split axis
    when each section has extent 1."""
    from ..ops.registry import _OPS
    from .register import make_eager

    fn = make_eager("_split_v2", _OPS["_split_v2"])
    out = fn(ary, indices_or_sections=indices_or_sections, axis=axis)
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    if squeeze_axis:
        outs = [o.squeeze(axis=axis) for o in outs]
    return outs


def Custom(*inputs, op_type=None, **kwargs):  # noqa: N802
    """Invoke a registered python CustomOp (reference: mx.nd.Custom)."""
    from ..operator import Custom as _custom

    return _custom(*inputs, op_type=op_type, **kwargs)


# numpy-frontend functions shared into the legacy namespace (NB: `concat` is
# NOT aliased to numpy concatenate — the registry installs the legacy
# concat(*data, dim=1) signature below)
from ..numpy import (  # noqa: F401,E402
    maximum,
    minimum,
    power,
)


# ---------------------------------------------------------------------------
# stateful ops that need RNG keys or mutation — hand-written, win over the
# generated wrappers below
# ---------------------------------------------------------------------------
def Dropout(data, p=0.5, mode="training", axes=None, **kwargs):  # noqa: ARG001, N802
    from ..numpy_extension import dropout as _npx_dropout

    return _npx_dropout(data, p=p, axes=axes, mode=mode)


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,  # noqa: N802
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, **kwargs):  # noqa: ARG001
    """Stateful nd.BatchNorm: updates the moving aux arrays in place when
    training, like the reference's mutable aux inputs (nn/batch_norm.cc)."""
    from ..numpy_extension import batch_norm as _bn

    return _bn(data, gamma, beta, moving_mean, moving_var, eps=eps,
               momentum=momentum, fix_gamma=fix_gamma,
               use_global_stats=use_global_stats,
               output_mean_var=output_mean_var, axis=axis)


def shuffle(data, **kwargs):  # noqa: ARG001
    from ..numpy.random import permutation

    return permutation(data)


# legacy top-level random_* names (reference: nd.random_uniform etc.) —
# aliases of the nd.random adapters; only exponential differs (the legacy op
# is parameterized by lam = 1/scale, sample_op.cc)
random_uniform = random.uniform
random_normal = random.normal
random_gamma = random.gamma
random_poisson = random.poisson
random_negative_binomial = random.negative_binomial
random_generalized_negative_binomial = random.generalized_negative_binomial
random_randint = random.randint


def random_exponential(lam=1.0, shape=None, dtype=None, ctx=None, out=None,
                       **kwargs):  # noqa: ARG001
    return random.exponential(1.0 / lam, shape=shape, dtype=dtype, out=out)


# sample_* variants (per-element distribution params, reference
# multisample_op.cc): params are arrays; shape extends on the right
def _sample(fn):
    def wrapped(*params, shape=None, dtype=None, **kwargs):  # noqa: ARG001
        base = tuple(params[0].shape) if hasattr(params[0], "shape") else ()
        extra = () if shape is None else (
            (shape,) if isinstance(shape, int) else tuple(shape))
        if extra:  # params broadcast against the appended sample dims
            params = [p.reshape(base + (1,) * len(extra))
                      if hasattr(p, "reshape") else p for p in params]
        return fn(*params, size=base + extra, dtype=dtype)
    return wrapped


from ..numpy import random as _npr  # noqa: E402

sample_uniform = _sample(_npr.uniform)
sample_normal = _sample(lambda mu, sigma, size=None, dtype=None:
                        _npr.normal(mu, sigma, size=size, dtype=dtype))
sample_gamma = _sample(lambda alpha, beta, size=None, dtype=None:
                       _npr.gamma(alpha, beta, size=size, dtype=dtype))
sample_exponential = _sample(lambda lam, size=None, dtype=None:
                             _npr.exponential(1.0 / lam, size=size,
                                              dtype=dtype))
sample_poisson = _sample(lambda lam, size=None, dtype=None:
                         _npr.poisson(lam, size=size, dtype=dtype))
sample_multinomial = random.multinomial  # legacy categorical sampler


def dropout(data, p=0.5, mode="training", axes=None, **kwargs):  # noqa: ARG001
    """Stateful lowercase alias — the registry's pure `dropout` needs an
    explicit key; this injects one like the reference's eager op."""
    return Dropout(data, p=p, mode=mode, axes=axes)


def RNN(data, parameters, state, state_cell=None, mode="lstm",  # noqa: N802
        state_size=None, num_layers=1, bidirectional=False, p=0.0,
        state_outputs=False, **kwargs):
    """Fused RNN op (reference: src/operator/rnn.cc `RNN`) — packed
    parameter vector, lax.scan time loop. Delegates to npx.rnn."""
    from ..numpy_extension import rnn as _rnn

    return _rnn(data=data, parameters=parameters, state=state,
                state_cell=state_cell, mode=mode, state_size=state_size,
                num_layers=num_layers, bidirectional=bidirectional, p=p,
                state_outputs=state_outputs, **kwargs)

# ---------------------------------------------------------------------------
# stateful optimizer update ops: reference semantics mutate the state
# tensors (mom/mean/var/...) in place and write the weight to `out`
# (src/operator/optimizer_op.cc FMutateInputs). The registry versions are
# pure (return tuples); these wrappers layer the in-place convention on
# top so ported update loops behave identically.
# ---------------------------------------------------------------------------
def _stateful_update(op_name, n_state):
    from ..ops.registry import get_op
    from .register import make_eager

    eager = make_eager(op_name, get_op(op_name))

    def wrapped(weight, grad, *args, out=None, **kwargs):
        states = list(args[:n_state])
        rest = args[n_state:]
        res = eager(weight, grad, *states, *rest, **kwargs)
        new_w = res[0]
        for st, new in zip(states, res[1:]):
            st._data = new._data
            st._version += 1
        if out is not None:
            out._data = new_w._data
            out._version += 1
            return out
        return new_w

    wrapped.__name__ = op_name
    return wrapped


for _opname, _nstate in [
    ("sgd_mom_update", 1), ("nag_mom_update", 1), ("signum_update", 1),
    ("adam_update", 2), ("adamw_update", 2), ("lamb_update_phase1", 2),
    ("rmsprop_update", 1), ("rmspropalex_update", 3), ("ftrl_update", 2),
    ("adagrad_update", 1), ("adadelta_update", 2),
]:
    globals()[_opname] = _stateful_update(_opname, _nstate)


# ---------------------------------------------------------------------------
# generated corpus: every registry op as an eager wrapper (legacy semantics —
# e.g. reductions take `exclude`, argmax returns float indices, reshape
# understands the 0/-1/-2/-3/-4 codes)
# ---------------------------------------------------------------------------
populate(globals())

# numpy names the legacy frontend also exposed that the registry doesn't cover
from ..numpy import (  # noqa: F401,E402
    add,
    multiply,
    subtract,
)

ElementWiseSum = globals()["add_n"]  # noqa: N816


# sparse classes at the package level (reference: from mxnet.ndarray
# import CSRNDArray — ndarray/__init__ re-exports sparse.*)
from .sparse import (  # noqa: F401,E402
    BaseSparseNDArray,
    CSRNDArray,
    RowSparseNDArray,
)


class CachedOp:
    """Callable compiled graph over a Symbol (reference:
    _ctypes/cached_op.py CachedOp — the imperative-invoke handle the
    frontends build from a symbol). TPU-native: the symbol lowers to a
    pure jax function jitted once; positional args bind to
    list_arguments() order, like the reference's C handle."""

    def __init__(self, sym, flags=(), thread_safe=False):  # noqa: ARG002
        import jax

        self._sym = sym
        self._arg_names = sym.list_arguments()
        self._jitted = jax.jit(sym._lower())

    def get_optimized_symbol(self):
        """The reference returns the pass-optimized symbol; XLA does the
        optimization below this API, so the original symbol IS the
        optimized graph handle."""
        return self._sym

    def __call__(self, *args, out=None, default_device=None,
                 default_ctx=None, **kwargs):  # noqa: ARG002
        # default_device/default_ctx: placement hint for 0-input graphs
        # (reference cached_op.py accepts it; placement is jax-managed)
        if kwargs:
            raise TypeError(
                f"CachedOp got unexpected keyword argument(s) "
                f"{sorted(kwargs)}; inputs are positional "
                f"({self._arg_names}) and only out= is accepted")
        if len(args) == 1 and args[0] is None and not self._arg_names:
            args = ()  # reference spelling: exe(None, default_device=...)
        if len(args) != len(self._arg_names):
            raise ValueError(
                f"CachedOp expects {len(self._arg_names)} inputs "
                f"({self._arg_names}), got {len(args)}")
        names = self._arg_names
        jitted = self._jitted

        def pure(*datas):
            return jitted(dict(zip(names, datas)))

        # apply_op: outputs join the autograd tape, so backward through
        # a CachedOp result works like any other op
        res = apply_op(pure, *args, name="CachedOp")
        outs = list(res) if isinstance(res, (list, tuple)) else [res]
        if out is not None:
            outs_l = out if isinstance(out, (list, tuple)) else [out]
            if len(outs_l) != len(outs):
                raise ValueError(
                    f"CachedOp produced {len(outs)} outputs but out= "
                    f"has {len(outs_l)} destinations")
            for o, r in zip(outs_l, outs):
                r.copyto(o)
            return out
        return outs if len(outs) > 1 else outs[0]


def __getattr__(name):
    """Resolve ops registered AFTER populate() ran (late module imports
    add registry entries — ctc_loss, amp_multicast; the symbol package
    has the same resync in its __getattr__)."""
    from ..ops.registry import _OPS

    fn = _OPS.get(name)
    if fn is not None:
        eager = make_eager(name, fn)
        globals()[name] = eager  # cache for next lookup
        return eager
    raise AttributeError(f"module 'mxnet_tpu.ndarray' has no attribute "
                         f"{name!r}")
