"""mx.nd — the legacy imperative NDArray namespace.

Reference: python/mxnet/ndarray/ (24k LoC of generated wrappers). In this
framework `mx.np` is the primary frontend; `mx.nd` re-exports the same NDArray
plus the common creation/math functions under their legacy names so
reference-era scripts keep working.
"""
from ..numpy import (  # noqa: F401
    arange,
    array,
    concatenate,
    full,
    linspace,
    ones,
    ones_like,
    zeros,
    zeros_like,
)
from . import sparse  # noqa: F401
from .ndarray import NDArray, apply_op, from_jax, waitall  # noqa: F401
from .utils import load, save, savez  # noqa: F401


def Custom(*inputs, op_type=None, **kwargs):  # noqa: N802
    """Invoke a registered python CustomOp (reference: mx.nd.Custom)."""
    from ..operator import Custom as _custom

    return _custom(*inputs, op_type=op_type, **kwargs)

concat = concatenate

# legacy op names commonly used in reference scripts
from ..numpy import (  # noqa: F401,E402
    abs,  # noqa: A004
    add,
    argmax,
    argmin,
    broadcast_to,
    clip,
    dot,
    exp,
    log,
    maximum,
    mean,
    minimum,
    multiply,
    power,
    sqrt,
    square,
    stack,
    subtract,
    sum,  # noqa: A004
    tanh,
    transpose,
    where,
)
from ..numpy.random import normal as random_normal  # noqa: E402
from ..numpy.random import uniform as random_uniform  # noqa: E402
