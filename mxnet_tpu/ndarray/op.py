"""`mx.nd.op` namespace (reference: mxnet/ndarray/op.py — every
registered op exposed flat). Mirrors the populated mx.nd surface."""


def __getattr__(name):
    from .. import ndarray as nd

    try:
        return getattr(nd, name)
    except AttributeError:
        raise AttributeError(f"mx.nd.op has no op {name!r}") from None


def __dir__():
    from .. import ndarray as nd

    return dir(nd)
