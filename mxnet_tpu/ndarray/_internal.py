"""Internal op namespace (reference: mxnet/ndarray/_internal.py — the
codegen target for `_`-prefixed ops). Attribute access resolves through
the op registry, same as _api_internal, wrapped eager (async dispatch +
autograd taping)."""
from ..ops.registry import _OPS
from .register import make_eager

_CACHE = {}


def __getattr__(name):
    if name in _CACHE:
        return _CACHE[name]
    for cand in (name, f"_{name}", f"_npi_{name}"):
        fn = _OPS.get(cand)
        if fn is not None:
            eager = _CACHE[name] = make_eager(cand, fn)
            return eager
    raise AttributeError(f"no registered internal op {name!r}")


def __dir__():
    return sorted(n for n in _OPS if n.startswith("_"))
