"""NDArray: an engine-tracked, mutable n-dim array over immutable jax.Arrays.

Re-design of the reference NDArray (include/mxnet/ndarray.h:81,
src/ndarray/ndarray.cc) for the XLA/PJRT world:

  * the reference's Chunk{storage, Engine::Var} pair becomes a single
    `jax.Array` handle — PJRT owns the HBM buffer, XLA tracks dependencies;
  * mutation (`a[:]=v`, `a+=b`, fused optimizer updates) is implemented by
    computing a fresh functional value and swapping the handle, bumping
    `_version` — exactly the reference's `ThreadedVar::version_` bump on a
    write dependency (src/engine/threaded_engine.h:122);
  * eager ops dispatch through `apply_op`, which (a) unwraps inputs,
    (b) runs the pure jax function (async on device), (c) wraps outputs, and
    (d) when autograd is recording, routes the call through `jax.vjp` and
    records a TapeNode — the analog of Imperative::Invoke + RecordOp
    (src/imperative/imperative.cc:105,235);
  * `wait_to_read` / `asnumpy` are the sync points, as in the reference
    (ndarray.h:394; NDArray::SyncCopyToCPU).

Sparse storage types (row_sparse/CSR) live in ndarray/sparse.py as a
storage + communication format (construction/cast/retain eager; sparse·dense
dot via XLA gather/segment_sum/scatter-add; kvstore row_sparse push/pull) —
see that module's docstring for the TPU design rationale.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .. import autograd as ag
from .. import engine
from ..base import MXNetError, normalize_dtype
from ..device import Device, current_device, from_jax_device
from ..telemetry import instruments as _telemetry

__all__ = ["NDArray", "apply_op", "array", "from_jax", "waitall"]

_Tracer = jax.core.Tracer


def _is_concrete(data):
    return not isinstance(data, _Tracer)


class NDArray:
    """Mutable array facade over a jax.Array (or a tracer during jit tracing)."""

    __array_priority__ = 1000.0

    __slots__ = (
        "_data",
        "_device",
        "_grad",
        "_grad_req",
        "_tape_entry",
        "_version",
        "__weakref__",
    )

    def __init__(self, data, device=None):
        self._data = data
        self._device = device
        self._grad = None
        self._grad_req = "null"
        self._tape_entry = None
        self._version = 0
        if _is_concrete(data):
            engine.track(self)

    # ------------------------------------------------------------------
    # metadata
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        s = 1
        for d in self._data.shape:
            s *= int(d)
        return s

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def device(self):
        if self._device is not None:
            return self._device
        if _is_concrete(self._data):
            devs = getattr(self._data, "devices", None)
            if devs is not None:
                return from_jax_device(next(iter(self._data.devices())))
        return current_device()

    # reference-compat aliases
    ctx = device
    context = device

    @property
    def stype(self):
        return "default"

    def tostype(self, stype):
        """Cast to a storage type ('default'/'csr'/'row_sparse');
        see ndarray/sparse.py for the TPU sparse design."""
        if stype == "default":
            return self
        from .sparse import cast_storage

        return cast_storage(self, stype)

    @property
    def grad(self):
        return self._grad

    @property
    def _requires_grad_entry(self):
        """True if ops consuming this array must be taped."""
        return self._tape_entry is not None or (
            self._grad is not None and self._grad_req != "null"
        )

    # ------------------------------------------------------------------
    # sync / host transfer
    # ------------------------------------------------------------------
    def wait_to_read(self):
        engine.wait_to_read(self)
        return self

    def wait_to_write(self):
        engine.wait_to_read(self)
        return self

    def asnumpy(self):
        """Blocking copy to host numpy (reference: NDArray::SyncCopyToCPU)."""
        if _is_concrete(self._data):
            _telemetry.record_transfer("d2h", _telemetry.nbytes_of(self._data))
        return _np.asarray(self._data)

    def item(self):
        return self.asnumpy().item()

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.item()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.item())
        raise ValueError(
            "The truth value of an array with more than one element is ambiguous"
        )

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        if _is_concrete(self._data):
            return f"{self.asnumpy()!r} <NDArray {self.shape} @{self.device}>"
        return f"<NDArray traced {self.shape} {self.dtype}>"

    # numpy protocol
    def __array__(self, dtype=None, copy=None):  # noqa: ARG002
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # NEP-13/NEP-18 dispatch (reference:
    # python/mxnet/numpy_dispatch_protocol.py:1-334): `onp.mean(mx_arr)`
    # runs the mx.np implementation ON DEVICE and returns an NDArray
    # instead of silently copying to host through __array__.
    _NOOP_KWARGS = ("out", "where", "casting", "order", "subok",
                    "signature")

    @staticmethod
    def _np_impl(name):
        from .. import numpy as _mxnp

        fn = getattr(_mxnp, name, None)
        if fn is None and hasattr(_mxnp, "linalg"):
            fn = getattr(_mxnp.linalg, name, None)
        return fn

    @staticmethod
    def _write_out(result, out):
        """Land `result` in a caller-supplied out buffer with numpy's
        shape/dtype contract (no silent reshapes)."""
        target = out[0] if isinstance(out, tuple) else out
        rdata = result._data if isinstance(result, NDArray) else result
        if tuple(rdata.shape) != tuple(target.shape):
            raise ValueError(
                f"non-broadcastable output operand with shape "
                f"{tuple(target.shape)} doesn't match the result shape "
                f"{tuple(rdata.shape)}")
        if isinstance(target, NDArray):
            if isinstance(result, NDArray) and \
                    result.dtype != target.dtype:
                # cast THROUGH the tape so the stored data and the taped
                # vjp node agree on dtype (else backward's cotangent
                # dtype mismatches)
                result = result.astype(target.dtype)
            target._data = result._data if isinstance(result, NDArray) \
                else rdata.astype(target._data.dtype)
            target._version += 1
            # an out= write must stay on the autograd tape exactly like
            # the expression it landed (cf. _assign_from)
            target._tape_entry = result._tape_entry \
                if isinstance(result, NDArray) else None
            return target
        # plain numpy out: copy device result to host (legacy behavior)
        _np.copyto(target, _np.asarray(rdata).astype(target.dtype))
        return target

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if method != "__call__":
            return NotImplemented
        out = kwargs.pop("out", None)
        if out is not None:
            target = out[0] if isinstance(out, tuple) else out
            if not isinstance(target, (NDArray, _np.ndarray)):
                return NotImplemented
        for k in NDArray._NOOP_KWARGS:
            if kwargs.get(k) is None:
                kwargs.pop(k, None)
        dtype = kwargs.pop("dtype", None)
        if kwargs and set(kwargs) - {"axis"}:
            return NotImplemented
        fn = NDArray._np_impl(ufunc.__name__)
        if fn is None:
            return NotImplemented
        result = fn(*inputs, **kwargs)
        if dtype is not None and isinstance(result, NDArray):
            result = result.astype(dtype)   # jnp ufuncs take no dtype=
        if out is not None:
            return NDArray._write_out(result, out)
        return result

    def __array_function__(self, func, types, args, kwargs):
        if not all(issubclass(t, (NDArray, _np.ndarray)) or
                   t in (int, float, bool, list, tuple) for t in types):
            return NotImplemented
        fn = NDArray._np_impl(func.__name__)
        if fn is None:
            return NotImplemented
        kwargs = dict(kwargs)
        out = kwargs.pop("out", None)
        if out is not None and not isinstance(
                out[0] if isinstance(out, tuple) else out,
                (NDArray, _np.ndarray)):
            return NotImplemented
        for k in NDArray._NOOP_KWARGS:
            if kwargs.get(k) is None:
                kwargs.pop(k, None)
        result = fn(*args, **kwargs)
        if out is not None:
            return NDArray._write_out(result, out)
        return result

    def __dlpack__(self, **kwargs):
        return self._data.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    # ------------------------------------------------------------------
    # autograd surface
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):  # noqa: ARG002
        """Attach a zero-initialized gradient buffer (reference:
        python/mxnet/ndarray/ndarray.py attach_grad). On an array that is
        already part of a recorded graph this RETAINS the mid-graph
        gradient: backward lands the array's output cotangent in .grad
        while still flowing through it (reference retain-grad
        semantics)."""
        self._grad = _wrap_out(jnp.zeros_like(self._data))
        self._grad_req = grad_req
        if self._tape_entry is not None:
            import weakref

            node, idx = self._tape_entry
            if node.vjp_fn is None:
                # producer tape already consumed: nothing can flow
                # through — this array becomes a fresh leaf (the old
                # detach semantics)
                self._tape_entry = None
                return self
            if node.retained is None:
                node.retained = []
            # re-attach replaces, never duplicates (each entry lands the
            # cotangent once)
            node.retained = [(r, i) for r, i in node.retained
                             if r() is not None and r() is not self]
            node.retained.append((weakref.ref(self), idx))
        return self

    def drop_grad(self):
        self._grad = None
        self._grad_req = "null"

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        ag.backward([self], [out_grad], retain_graph=retain_graph,
                    train_mode=train_mode)

    def detach(self):
        out = NDArray(self._data, self._device)
        return out

    # ------------------------------------------------------------------
    # device movement / copies
    # ------------------------------------------------------------------
    def as_in_context(self, device):
        return self.as_in_ctx(device)

    def as_in_ctx(self, device):
        device = Device(device) if not isinstance(device, Device) else device
        if self.device == device:
            return self
        return self.copyto(device)

    to_device = as_in_ctx

    def copyto(self, other):
        """Copy to a device or into another NDArray (reference: CopyFromTo,
        src/ndarray/ndarray.cc:1370)."""
        if isinstance(other, (Device, NDArray)) and _is_concrete(self._data):
            _telemetry.record_transfer("d2d", _telemetry.nbytes_of(self._data))
        if isinstance(other, Device):
            data = jax.device_put(self._data, other.jax_device)
            return NDArray(data, other)
        if isinstance(other, NDArray):
            other._data = jax.device_put(self._data, other.device.jax_device)
            other._version += 1
            return other
        raise TypeError(f"copyto does not support type {type(other)}")

    def copy(self):
        return _wrap_out(jnp.copy(self._data), self._device)

    def astype(self, dtype, copy=True):
        dtype = normalize_dtype(dtype)
        if not copy and self.dtype == dtype:
            return self
        return apply_op(lambda x: x.astype(dtype), self)

    def as_np_ndarray(self):
        return self

    def as_nd_ndarray(self):
        return self

    # ------------------------------------------------------------------
    # shape manipulation (differentiable, taped via apply_op)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        """Reshape supporting the reference's special codes on the METHOD
        (reference: ndarray/ndarray.py:1446-1501 — 0 copy-dim, -1 infer,
        -2 copy-rest, -3 merge-two, -4 split, `reverse=1` right-to-left).

        One class serves both frontends here, so dispatch is by content:
        plain dims and -1 are numpy-identical; -2/-3/-4, `reverse`, and a
        0 against a non-empty array (numpy would error) take the legacy
        path. A 0 with an empty array keeps numpy semantics."""
        reverse = bool(kwargs.pop("reverse", False))
        if not shape and "shape" in kwargs:
            shape = (kwargs.pop("shape"),)  # a.reshape(shape=(m, n))
        kwargs.pop("order", None)  # numpy-style kwarg; only 'C' layouts here
        if kwargs:
            raise TypeError(f"reshape got unexpected kwargs {sorted(kwargs)}")
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = tuple(int(d) for d in shape)
        legacy = reverse or any(d in (-2, -3, -4) for d in shape) \
            or (0 in shape and self.size != 0)
        if legacy:
            from ..ops.tensor import legacy_reshape_shape

            new_shape = legacy_reshape_shape(self.shape, shape, reverse)
            return apply_op(lambda x: jnp.reshape(x, new_shape), self)
        return apply_op(lambda x: jnp.reshape(x, shape), self)

    def transpose(self, *axes, **kwargs):
        if not axes and kwargs.get("axes") is not None:
            axes = (kwargs.pop("axes"),)  # legacy kwarg spelling
        else:
            kwargs.pop("axes", None)  # axes=None == reverse all
        if kwargs:
            raise TypeError(
                f"transpose got unexpected kwargs {sorted(kwargs)}")
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        ax = axes if axes else None
        return apply_op(lambda x: jnp.transpose(x, ax), self)

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        return self.reshape((-1,))

    def squeeze(self, axis=None):
        return apply_op(lambda x: jnp.squeeze(x, axis), self)

    def expand_dims(self, axis):
        return apply_op(lambda x: jnp.expand_dims(x, axis), self)

    def swapaxes(self, a1, a2):
        return apply_op(lambda x: jnp.swapaxes(x, a1, a2), self)

    def repeat(self, repeats, axis=None):
        return apply_op(lambda x: jnp.repeat(x, repeats, axis), self)

    def broadcast_to(self, shape):
        return apply_op(lambda x: jnp.broadcast_to(x, shape), self)

    def split(self, indices_or_sections=None, axis=None, num_outputs=None,
              squeeze_axis=False):
        if num_outputs is not None:
            # legacy spelling (reference nd.split: num_outputs/squeeze_axis,
            # default axis=1 — slice_channel in matrix_op.cc)
            from .. import ndarray as _nd_ns

            return _nd_ns.split(self, num_outputs=num_outputs,
                                axis=1 if axis is None else axis,
                                squeeze_axis=squeeze_axis)
        if squeeze_axis:
            # loud: the legacy kwarg only applies with num_outputs= —
            # silently splitting on numpy's axis-0 default instead would
            # hand back wrongly-shaped sections
            raise TypeError(
                "split: squeeze_axis requires the legacy num_outputs= "
                "spelling (a.split(num_outputs=2, squeeze_axis=True)); "
                "positional arg means numpy indices_or_sections here — "
                "see docs/migration.md")
        return self._split_np(indices_or_sections,
                              0 if axis is None else axis)

    def _split_np(self, indices_or_sections, axis=0):
        return apply_op(
            lambda x: tuple(jnp.split(x, indices_or_sections, axis)), self
        )

    def take(self, indices, axis=None, mode="clip"):
        # float indices cast (both reference classes tolerate them —
        # legacy arrays default to float32, indexing_op.h casts);
        # python ints/lists pass through jnp.asarray first
        def pure(x, i):
            i = jnp.asarray(i)
            if not (jnp.issubdtype(i.dtype, jnp.integer)
                    or i.dtype == jnp.bool_):
                i = i.astype(jnp.int32)
            return jnp.take(x, i, axis=axis, mode=mode)

        return apply_op(pure, self, indices)

    def clip(self, a_min=None, a_max=None):
        return apply_op(lambda x: jnp.clip(x, a_min, a_max), self)

    def zeros_like(self):
        return _wrap_out(jnp.zeros_like(self._data), self._device)

    def ones_like(self):
        return _wrap_out(jnp.ones_like(self._data), self._device)

    def tolist(self):
        return self.asnumpy().tolist()

    # reductions / common math as methods
    def sum(self, axis=None, keepdims=False, dtype=None):
        return apply_op(
            lambda x: jnp.sum(x, axis=axis, keepdims=keepdims,
                              dtype=normalize_dtype(dtype)), self)

    def mean(self, axis=None, keepdims=False, dtype=None):
        return apply_op(
            lambda x: jnp.mean(x, axis=axis, keepdims=keepdims,
                               dtype=normalize_dtype(dtype)), self)

    def max(self, axis=None, keepdims=False):
        return apply_op(lambda x: jnp.max(x, axis=axis, keepdims=keepdims), self)

    def min(self, axis=None, keepdims=False):
        return apply_op(lambda x: jnp.min(x, axis=axis, keepdims=keepdims), self)

    def prod(self, axis=None, keepdims=False):
        return apply_op(lambda x: jnp.prod(x, axis=axis, keepdims=keepdims), self)

    def any(self, axis=None, keepdims=False):
        return apply_op(lambda x: jnp.any(x, axis=axis, keepdims=keepdims),
                        self)

    def all(self, axis=None, keepdims=False):
        return apply_op(lambda x: jnp.all(x, axis=axis, keepdims=keepdims),
                        self)

    def argmax(self, axis=None):
        return apply_op(lambda x: jnp.argmax(x, axis=axis), self)

    def argmin(self, axis=None):
        return apply_op(lambda x: jnp.argmin(x, axis=axis), self)

    def std(self, axis=None, ddof=0, keepdims=False):
        return apply_op(
            lambda x: jnp.std(x, axis=axis, ddof=ddof, keepdims=keepdims), self)

    def var(self, axis=None, ddof=0, keepdims=False):
        return apply_op(
            lambda x: jnp.var(x, axis=axis, ddof=ddof, keepdims=keepdims), self)

    def cumsum(self, axis=None, dtype=None):
        return apply_op(
            lambda x: jnp.cumsum(x, axis=axis, dtype=normalize_dtype(dtype)), self)

    def dot(self, other):
        return apply_op(jnp.dot, self, other)

    def abs(self):
        return apply_op(jnp.abs, self)

    def sqrt(self):
        return apply_op(jnp.sqrt, self)

    def exp(self):
        return apply_op(jnp.exp, self)

    def log(self):
        return apply_op(jnp.log, self)

    def round(self, decimals=0):
        return apply_op(lambda x: jnp.round(x, decimals), self)

    def sigmoid(self):
        return apply_op(jax.nn.sigmoid, self)

    def relu(self):
        return apply_op(jax.nn.relu, self)

    def tanh(self):
        return apply_op(jnp.tanh, self)

    def norm(self, ord=None, axis=None, keepdims=False):
        return apply_op(
            lambda x: jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keepdims),
            self)

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    @staticmethod
    def _int_key(k):
        """Float index arrays cast to int32 here, ONCE for every indexing
        consumer (reference indexing_op.h casts; legacy index arrays
        default to float32). Bool masks pass through."""
        if hasattr(k, "dtype") and not (
                _np.issubdtype(k.dtype, _np.integer)
                or k.dtype == bool or str(k.dtype) == "bool"):
            return k.astype(jnp.int32)
        return k

    def _index(self, key):
        if isinstance(key, NDArray):
            return self._int_key(key._data)
        if isinstance(key, tuple):
            return tuple(self._int_key(k._data) if isinstance(k, NDArray)
                         else k for k in key)
        if isinstance(key, list):
            # numpy/reference semantics: a[[0, 2, 3]] is fancy indexing;
            # jnp rejects raw list indices
            return _np.asarray(key)
        return key

    def __getitem__(self, key):
        key = self._index(key)
        return apply_op(lambda x: x[key], self)

    def __setitem__(self, key, value):
        """In-place write: functional scatter + handle swap + version bump."""
        key = self._index(key)
        if isinstance(value, NDArray):
            new = apply_op(
                lambda x, v: x.at[key].set(v.astype(x.dtype)), self, value)
        else:
            new = apply_op(lambda x: x.at[key].set(value), self)
        self._assign_from(new)

    def _assign_from(self, other):
        self._data = other._data
        self._tape_entry = other._tape_entry
        self._version += 1

    # ------------------------------------------------------------------
    # arithmetic operators
    # ------------------------------------------------------------------
    def _binary(self, other, fn, reverse=False):
        if isinstance(other, NDArray):
            if reverse:
                return apply_op(fn, other, self)
            return apply_op(fn, self, other)
        if reverse:
            return apply_op(lambda x: fn(other, x), self)
        return apply_op(lambda x: fn(x, other), self)

    def __add__(self, o):
        return self._binary(o, jnp.add)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, jnp.subtract)

    def __rsub__(self, o):
        return self._binary(o, jnp.subtract, reverse=True)

    def __mul__(self, o):
        return self._binary(o, jnp.multiply)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, jnp.divide)

    def __rtruediv__(self, o):
        return self._binary(o, jnp.divide, reverse=True)

    def __floordiv__(self, o):
        return self._binary(o, jnp.floor_divide)

    def __rfloordiv__(self, o):
        return self._binary(o, jnp.floor_divide, reverse=True)

    def __mod__(self, o):
        return self._binary(o, jnp.mod)

    def __rmod__(self, o):
        return self._binary(o, jnp.mod, reverse=True)

    def __pow__(self, o):
        return self._binary(o, jnp.power)

    def __rpow__(self, o):
        return self._binary(o, jnp.power, reverse=True)

    def __matmul__(self, o):
        return self._binary(o, jnp.matmul)

    def __rmatmul__(self, o):
        return self._binary(o, jnp.matmul, reverse=True)

    def __neg__(self):
        return apply_op(jnp.negative, self)

    def __pos__(self):
        return self

    def __abs__(self):
        return apply_op(jnp.abs, self)

    def __invert__(self):
        return apply_op(jnp.invert, self)

    # comparisons
    def __eq__(self, o):
        return self._binary(o, lambda a, b: a == b)

    def __ne__(self, o):
        return self._binary(o, lambda a, b: a != b)

    def __lt__(self, o):
        return self._binary(o, lambda a, b: a < b)

    def __le__(self, o):
        return self._binary(o, lambda a, b: a <= b)

    def __gt__(self, o):
        return self._binary(o, lambda a, b: a > b)

    def __ge__(self, o):
        return self._binary(o, lambda a, b: a >= b)

    __hash__ = object.__hash__

    # logical
    def __and__(self, o):
        return self._binary(o, jnp.bitwise_and)

    def __or__(self, o):
        return self._binary(o, jnp.bitwise_or)

    def __xor__(self, o):
        return self._binary(o, jnp.bitwise_xor)

    # in-place: compute functionally, swap handle (version bump)
    def _inplace(self, other, fn):
        new = self._binary(other, fn)
        self._assign_from(new)
        return self

    def __iadd__(self, o):
        return self._inplace(o, jnp.add)

    def __isub__(self, o):
        return self._inplace(o, jnp.subtract)

    def __imul__(self, o):
        return self._inplace(o, jnp.multiply)

    def __itruediv__(self, o):
        return self._inplace(o, jnp.divide)

    def __imod__(self, o):
        return self._inplace(o, jnp.mod)

    # fluent method surface (reference: ndarray.py hand-writes one method
    # per op — `a.topk(...)` == `mx.nd.topk(a, ...)`, test_ndarray.py:1286
    # test_ndarray_fluent). Here any registered op resolves as a method
    # through the eager nd namespace; explicit methods above keep
    # priority (normal attribute lookup wins over __getattr__).
    def __getattr__(self, name):
        if name.startswith("_"):  # never intercept protocol/dunder probes
            raise AttributeError(name)
        from .. import ndarray as _nd_ns

        fn = getattr(_nd_ns, name, None)
        if callable(fn):
            import functools

            return functools.partial(fn, self)
        raise AttributeError(
            f"'NDArray' object has no attribute {name!r}")


# ---------------------------------------------------------------------------
# op application (the Imperative::Invoke analog)
# ---------------------------------------------------------------------------

def _wrap_out(data, device=None):
    return NDArray(data, device)


def _is_sparse(a):
    return getattr(a, "stype", None) in ("csr", "row_sparse")


def densify_sparse_args(args):
    """Storage fallback (reference FComputeExFallback): sparse operands
    of ops without a sparse kernel densify at the eager boundary, so
    nd.sum(csr) / nd.where(csr, ...) value-match the reference with a
    dense result. Shared by apply_op and make_eager — keep the
    semantics in ONE place. Accepts a tuple/list of positionals or a
    dict of keywords."""
    if isinstance(args, dict):
        if any(_is_sparse(v) for v in args.values()):
            return {k: v.todense() if _is_sparse(v) else v
                    for k, v in args.items()}
        return args
    if any(_is_sparse(a) for a in args):
        return tuple(a.todense() if _is_sparse(a) else a for a in args)
    return args


def apply_op(fn, *args, name=None):
    """Run pure jax function `fn` over NDArray/raw args; tape when recording.

    `fn` receives raw jax arrays in the positions where NDArrays were passed;
    other args go through untouched. Returns NDArray or tuple of NDArrays,
    mirroring fn's output structure.
    """
    args = densify_sparse_args(args)
    nd_pos = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    datas = [args[i]._data for i in nd_pos]

    if len(nd_pos) == len(args):
        base = fn
    else:
        def base(*xs):
            call = list(args)
            for i, x in zip(nd_pos, xs):
                call[i] = x
            return fn(*call)

    def pure(*xs):
        r = base(*xs)
        # normalize list outputs (e.g. jnp.split) to tuples so the tape's
        # tuple cotangents match the vjp's recorded output pytree
        return tuple(r) if isinstance(r, list) else r

    record = ag.taping_active() and any(
        args[i]._requires_grad_entry for i in nd_pos
    )

    if record:
        out, vjp_fn = jax.vjp(pure, *datas)
    else:
        out = pure(*datas)

    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    wrapped = [_wrap_out(o) for o in outs]

    if record:
        nd_inputs = [args[i] for i in nd_pos]
        node = ag.TapeNode(
            vjp_fn,
            nd_inputs,
            [a._tape_entry for a in nd_inputs],
            [(tuple(o.shape), o.dtype) for o in outs],
            multi_out=multi,
            name=name or getattr(fn, "__name__", "op"),
            pure_fn=pure,
            input_datas=datas,
        )
        for idx, w in enumerate(wrapped):
            w._tape_entry = (node, idx)

    return tuple(wrapped) if multi else wrapped[0]


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def _creation_device(device):
    if device is None:
        return current_device()
    return device if isinstance(device, Device) else Device(device)


def from_jax(data, device=None):
    return NDArray(data, device)


def array(source, dtype=None, device=None, ctx=None):
    """Create an NDArray on `device` from array-like/NDArray."""
    device = _creation_device(device if device is not None else ctx)
    dtype = normalize_dtype(dtype)
    if isinstance(source, NDArray):
        data = source._data
        if dtype is not None and data.dtype != dtype:
            data = data.astype(dtype)
        return NDArray(jax.device_put(data, device.jax_device), device)
    from_numpy = isinstance(source, _np.ndarray)
    arr = _np.asarray(source)
    if dtype is None:
        if not from_numpy and arr.dtype.kind in "iuf":
            # python lists/scalars default to the float dtype (reference:
            # ndarray.py array — 'float32 otherwise'; f64 under
            # npx.set_np(dtype=True), test_numpy_default_dtype.py).
            # bool/complex inputs keep their kind.
            from ..numpy_extension import default_float_dtype

            dtype = _np.dtype(default_float_dtype())
        elif arr.dtype == _np.float64:
            dtype = _np.dtype(_np.float32)  # documented 32-bit default
        elif arr.dtype == _np.int64:
            dtype = _np.dtype(_np.int32)  # 32-bit creation default
    if dtype is not None:
        arr = arr.astype(dtype)
    _telemetry.record_transfer("h2d", arr.nbytes)
    return NDArray(jax.device_put(arr, device.jax_device), device)


def waitall():
    engine.waitall()
