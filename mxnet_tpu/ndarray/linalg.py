"""mx.nd.linalg — the la_op family under its submodule names
(reference: python/mxnet/ndarray/linalg.py — potrf/gemm/trsm/... without the
`linalg_` prefix)."""
from __future__ import annotations

from .register import populate

populate(globals(), predicate=lambda n: n.startswith("linalg_"),
         rename=lambda n: n[len("linalg_"):])
